"""MOJO export/offline-scoring parity — the "same answer everywhere"
guarantee (reference tier: testdir_javapredict cross-language consistency,
SURVEY.md §4 item 6): in-cluster predictions must equal genmodel scoring.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.genmodel import EasyPredictModelWrapper, load_mojo


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _frame_rows(frame: Frame):
    """Frame -> list of row dicts with domain strings for cats."""
    df = frame.to_pandas()
    return df.to_dict(orient="records")


def _mixed_frame(rng, n=400, classify=True):
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n) * 2 + 1
    g = rng.integers(0, 3, size=n)
    logit = x0 - 0.8 * x1 + np.array([0.5, -0.5, 1.0])[g]
    if classify:
        y = (logit + rng.normal(size=n) * 0.5 > 0).astype(np.int32)
        ycol = Column("y", y, ColType.CAT, ["no", "yes"])
    else:
        ycol = Column("y", logit + rng.normal(size=n) * 0.1)
    return Frame(
        [
            Column("x0", x0),
            Column("x1", x1),
            Column("g", g.astype(np.int32), ColType.CAT, ["a", "b", "c"]),
            ycol,
        ]
    )


def _assert_parity(model, frame, mojo_path, atol=1e-5):
    model.download_mojo(mojo_path)
    mm = load_mojo(mojo_path)
    ours = model._predict_raw(frame)
    theirs = mm.score(_frame_rows(frame))
    np.testing.assert_allclose(
        np.asarray(theirs, dtype=np.float64),
        np.asarray(ours, dtype=np.float64),
        atol=atol, rtol=1e-4,
    )
    return mm


class TestMojoParity:
    def test_glm_binomial(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM

        fr = _mixed_frame(rng)
        m = GLM(response_column="y", family="binomial", lambda_=0.01).train(fr)
        mm = _assert_parity(m, fr, str(tmp_path / "glm.mojo"))
        pred = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0])
        assert pred.label in ("no", "yes")
        assert len(pred.class_probabilities) == 2

    def test_glm_regression(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM

        fr = _mixed_frame(rng, classify=False)
        m = GLM(response_column="y", family="gaussian").train(fr)
        mm = _assert_parity(m, fr, str(tmp_path / "glm_reg.mojo"))
        val = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0]).value
        assert np.isfinite(val)

    def test_gbm(self, rng, tmp_path):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _mixed_frame(rng)
        m = GBM(response_column="y", ntrees=10, max_depth=3, seed=1).train(fr)
        _assert_parity(m, fr, str(tmp_path / "gbm.mojo"))

    def test_drf_multinomial(self, rng, tmp_path):
        from h2o3_tpu.models.tree.drf import DRF

        n = 500
        X = rng.normal(size=(n, 3))
        y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
        fr = Frame(
            [Column(f"x{i}", X[:, i]) for i in range(3)]
            + [Column("y", y.astype(np.int32), ColType.CAT, ["l", "m", "h"])]
        )
        m = DRF(response_column="y", ntrees=8, max_depth=4, seed=3).train(fr)
        mm = _assert_parity(m, fr, str(tmp_path / "drf.mojo"))
        pred = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0])
        assert pred.label in ("l", "m", "h")

    def test_kmeans(self, rng, tmp_path):
        from h2o3_tpu.models.kmeans import KMeans

        fr = _mixed_frame(rng, classify=False)
        m = KMeans(k=3, seed=5, ignored_columns=["y"]).train(fr)
        mm = _assert_parity(m, fr, str(tmp_path / "km.mojo"))
        pred = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0])
        assert 0 <= pred.cluster < 3
        assert len(pred.distances) == 3

    def test_deeplearning(self, rng, tmp_path):
        from h2o3_tpu.models.deeplearning import DeepLearning

        fr = _mixed_frame(rng)
        m = DeepLearning(
            response_column="y", hidden=[8, 8], epochs=3, seed=2
        ).train(fr)
        _assert_parity(m, fr, str(tmp_path / "dl.mojo"), atol=1e-4)

    def test_naive_bayes(self, rng, tmp_path):
        from h2o3_tpu.models.naive_bayes import NaiveBayes

        fr = _mixed_frame(rng)
        m = NaiveBayes(response_column="y").train(fr)
        _assert_parity(m, fr, str(tmp_path / "nb.mojo"))

    def test_isolation_forest(self, rng, tmp_path):
        from h2o3_tpu.models.isolation_forest import IsolationForest

        fr = _mixed_frame(rng, classify=False)
        m = IsolationForest(
            ntrees=10, max_depth=6, seed=4, ignored_columns=["y"]
        ).train(fr)
        mm = _assert_parity(m, fr, str(tmp_path / "if.mojo"))
        pred = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0])
        assert 0.0 <= pred.score <= 1.0

    def test_pca(self, rng, tmp_path):
        from h2o3_tpu.models.pca import PCA

        fr = _mixed_frame(rng, classify=False)
        m = PCA(k=2, ignored_columns=["y"]).train(fr)
        mm = _assert_parity(m, fr, str(tmp_path / "pca.mojo"))
        dims = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0]).dimensions
        assert len(dims) == 2

    def test_unseen_level_and_missing_values(self, rng, tmp_path):
        """adaptTestForTrain semantics survive export: unseen level -> NA."""
        from h2o3_tpu.models.glm import GLM

        fr = _mixed_frame(rng)
        m = GLM(response_column="y", family="binomial").train(fr)
        p = str(tmp_path / "glm2.mojo")
        m.download_mojo(p)
        mm = load_mojo(p)
        row = {"x0": 0.5, "x1": None, "g": "NEVER_SEEN"}
        probs = mm.score0(row)
        assert np.all(np.isfinite(probs))
        assert abs(probs.sum() - 1.0) < 1e-9

    def test_genmodel_has_no_jax_dependency(self):
        """The genmodel package must stay numpy-only (dependency-light jar).

        PYTHONPATH is cleared because this machine's sitecustomize preloads
        jax into every interpreter; the check is what *genmodel* imports."""
        import os
        import subprocess
        import sys

        code = (
            "import sys\n"
            "preloaded = 'jax' in sys.modules\n"
            "import h2o3_tpu.genmodel\n"
            "assert preloaded or 'jax' not in sys.modules, 'genmodel imported jax'\n"
            "assert 'h2o3_tpu.models' not in sys.modules\n"
            "assert 'h2o3_tpu.frame' not in sys.modules\n"
            "print('clean')\n"
        )
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["PYTHONPATH"] = "/root/repo"
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            cwd="/root/repo", env=env,
        )
        assert out.returncode == 0, out.stderr
        assert "clean" in out.stdout


class TestMojoReviewFixes:
    def test_glm_offset_parity(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM

        n = 300
        x = rng.normal(size=n)
        off = rng.uniform(0.0, 2.0, size=n)
        y = rng.poisson(np.exp(0.4 * x + off)).astype(np.float64)
        fr = Frame([Column("x", x), Column("exposure", off), Column("y", y)])
        m = GLM(
            response_column="y", family="poisson", offset_column="exposure",
            ignored_columns=["exposure"],
        ).train(fr)
        p = str(tmp_path / "glm_off.mojo")
        m.download_mojo(p)
        from h2o3_tpu.genmodel import load_mojo

        mm = load_mojo(p)
        rows = [{"x": float(x[i]), "exposure": float(off[i])} for i in range(50)]
        theirs = mm.score(rows)
        ours = m._predict_raw(fr.head(50))
        np.testing.assert_allclose(theirs, ours, rtol=1e-6)

    def test_binomial_label_threshold_matches_in_cluster(self, rng, tmp_path):
        from h2o3_tpu.models.tree.gbm import GBM

        # imbalanced so max-F1 threshold is far from 0.5
        n = 800
        X = rng.normal(size=(n, 3))
        y = ((X[:, 0] + rng.normal(size=n)) > 1.6).astype(np.int32)
        fr = Frame(
            [Column(f"x{i}", X[:, i]) for i in range(3)]
            + [Column("y", y, ColType.CAT, ["neg", "pos"])]
        )
        m = GBM(response_column="y", ntrees=10, max_depth=3, seed=1).train(fr)
        p = str(tmp_path / "imb.mojo")
        m.download_mojo(p)
        from h2o3_tpu.genmodel import load_mojo

        mm = load_mojo(p)
        w = EasyPredictModelWrapper(mm)
        online = m.predict(fr)
        pc = online.col("predict")
        rows = _frame_rows(fr)
        for i in range(0, n, 37):
            r = dict(rows[i]); r.pop("y", None)
            assert w.predict(r).label == pc.domain[pc.data[i]]

    def test_autoencoder_easy_predict(self, rng, tmp_path):
        from h2o3_tpu.models.deeplearning import DeepLearning

        fr = _mixed_frame(rng, classify=False)
        m = DeepLearning(
            autoencoder=True, hidden=[4], epochs=2, seed=1, ignored_columns=["y"]
        ).train(fr)
        p = str(tmp_path / "ae.mojo")
        m.download_mojo(p)
        from h2o3_tpu.genmodel import load_mojo

        mm = load_mojo(p)
        pred = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0])
        assert hasattr(pred, "reconstructed")
        assert pred.reconstruction_error is not None
        assert np.isfinite(pred.reconstruction_error)


class TestMojoGlmR3:
    """Round-3 GLM families through the MOJO (multinomial softmax + ordinal
    thresholds; reference scorer hex/genmodel/algos/glm/GlmMojoModel.java and
    GlmOrdinalMojoModel.java)."""

    def test_glm_multinomial(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM

        n = 300
        X = rng.normal(size=(n, 3))
        y = np.array(["a", "b", "c"])[
            np.argmax(X @ rng.normal(size=(3, 3)), axis=1)
        ]
        fr = Frame(
            [Column(f"x{i}", X[:, i]) for i in range(3)]
            + [Column("y", np.searchsorted(["a", "b", "c"], y).astype(np.int32),
                      ColType.CAT, ["a", "b", "c"])]
        )
        m = GLM(response_column="y", family="multinomial", lambda_=0.01).train(fr)
        mm = _assert_parity(m, fr, str(tmp_path / "glm_mn.mojo"))
        pred = EasyPredictModelWrapper(mm).predict(_frame_rows(fr)[0])
        assert pred.label in ("a", "b", "c")
        assert len(pred.class_probabilities) == 3

    def test_glm_ordinal(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM

        n = 500
        X = rng.normal(size=(n, 2))
        eta = X @ np.array([1.0, -0.8])
        u = rng.random(n)
        c0 = 1 / (1 + np.exp(-(-0.5 - eta)))
        c1 = 1 / (1 + np.exp(-(1.0 - eta)))
        codes = np.where(u < c0, 0, np.where(u < c1, 1, 2)).astype(np.int32)
        fr = Frame(
            [Column("x0", X[:, 0]), Column("x1", X[:, 1]),
             Column("y", codes, ColType.CAT, ["lo", "mid", "hi"])]
        )
        m = GLM(response_column="y", family="ordinal", lambda_=0.0).train(fr)
        _assert_parity(m, fr, str(tmp_path / "glm_ord.mojo"))


def test_pca_demean_descale_mojo_roundtrip(rng, tmp_path):
    """The native MOJO must carry demean/descale statistics — without
    them the offline scorer projects un-transformed rows onto
    transformed-space eigenvectors."""
    import numpy as np

    from h2o3_tpu.frame.frame import Column, Frame
    from h2o3_tpu.genmodel.mojo_model import MojoModel
    from h2o3_tpu.models.mojo_export import write_mojo as write_native
    from h2o3_tpu.models.pca import PCA

    X = rng.normal(size=(250, 4)) + 5.0
    X[:, 0] *= 10.0
    fr = Frame([Column(f"x{i}", X[:, i]) for i in range(4)])
    for transform in ("demean", "descale"):
        m = PCA(k=2, transform=transform, seed=1).train(fr)
        path = str(tmp_path / f"pca_{transform}.mojo")
        write_native(m, path)
        mojo = MojoModel.load(path)
        got = mojo.score({f"x{i}": X[:, i] for i in range(4)})
        want = m._predict_raw(fr)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
