"""Ingest breadth: SVMLight/ARFF parsers, gzip/zip decompression, glob &
multi-file import, URI-scheme Persist dispatch — VERDICT r2 item 6.

Reference: water/parser/{SVMLightParser,ARFFParser,ZipUtil},
water/persist/PersistManager.java, ParseDataset multi-file parse."""

import gzip
import json
import os
import urllib.request
import zipfile

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType
from h2o3_tpu.frame.ingest import (
    import_parse,
    list_sources,
    parse_arff,
    parse_source,
    parse_svmlight,
    resolve_persist,
    sniff_format,
)

# legacy module predating the CheckKeysTask fixture: the REST
# import tests leave parsed frames behind; the module-level
# sweeper removes everything at module end
pytestmark = pytest.mark.leaks_keys

SVM = """\
1 1:0.5 3:2.0  # comment
-1 2:1.5
1 1:1.0 2:2.0 3:3.0
"""

ARFF = """\
% a comment
@RELATION weather
@ATTRIBUTE outlook {sunny, overcast, rainy}
@ATTRIBUTE temperature NUMERIC
@ATTRIBUTE humidity real
@ATTRIBUTE windy {TRUE, FALSE}
@ATTRIBUTE play string
@DATA
sunny,85,85,FALSE,no
overcast,83,?,TRUE,yes
rainy,?,96,FALSE,yes
"""

CSV = "a,b\n1,x\n2,y\n3,z\n"


class TestSvmLight:
    def test_parse(self):
        fr = parse_svmlight(SVM)
        assert fr.names == ["target", "C1", "C2", "C3"]
        np.testing.assert_array_equal(
            fr.col("target").data, [1.0, -1.0, 1.0]
        )
        # absent entries are 0 (sparse semantics), not NA
        np.testing.assert_array_equal(fr.col("C2").data, [0.0, 1.5, 2.0])
        np.testing.assert_array_equal(fr.col("C3").data, [2.0, 0.0, 3.0])

    def test_bad_index_order_raises(self):
        with pytest.raises(ValueError, match="increasing"):
            parse_svmlight("1 3:1 2:1\n")

    def test_sniff(self):
        assert sniff_format("x.svm", b"") == "svmlight"
        assert sniff_format("data.txt", SVM.encode()) == "svmlight"


class TestArff:
    def test_parse(self):
        fr = parse_arff(ARFF)
        assert fr.names == ["outlook", "temperature", "humidity", "windy", "play"]
        out = fr.col("outlook")
        assert out.type is ColType.CAT
        # declared domain order preserved (not data-sorted)
        assert out.domain == ["sunny", "overcast", "rainy"]
        np.testing.assert_array_equal(out.data, [0, 1, 2])
        temp = fr.col("temperature")
        assert temp.type is ColType.NUM
        assert np.isnan(temp.data[2])  # '?' is NA
        assert fr.col("play").type is ColType.STR

    def test_sniff(self):
        assert sniff_format("weather.arff", b"") == "arff"
        assert sniff_format("w.txt", ARFF.encode()) == "arff"

    def test_sparse_rows_rejected(self):
        arff = "@relation r\n@attribute a numeric\n@data\n{0 5}\n"
        with pytest.raises(ValueError, match="sparse"):
            parse_arff(arff)


class TestDecompression:
    def test_gzip(self, tmp_path):
        p = tmp_path / "data.csv.gz"
        p.write_bytes(gzip.compress(CSV.encode()))
        fr = parse_source(str(p))
        assert fr.nrows == 3 and fr.names == ["a", "b"]

    def test_zip_single(self, tmp_path):
        p = tmp_path / "data.zip"
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("inner.csv", CSV)
        fr = parse_source(str(p))
        assert fr.nrows == 3

    def test_zip_of_gzip_of_svm(self, tmp_path):
        """nested wrapping unwraps recursively (ZipUtil semantics)."""
        p = tmp_path / "d.zip"
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("inner.svm.gz", gzip.compress(SVM.encode()))
        fr = parse_source(str(p))
        assert fr.names[0] == "target"


class TestMultiFileImport:
    def test_glob_rbind(self, tmp_path):
        (tmp_path / "part1.csv").write_text("a,b\n1,x\n2,y\n")
        (tmp_path / "part2.csv").write_text("a,b\n3,z\n")
        fr = import_parse(str(tmp_path / "part*.csv"))
        assert fr.nrows == 3
        assert set(fr.col("b").domain) >= {"x", "y", "z"}

    def test_directory_import(self, tmp_path):
        (tmp_path / "p1.csv").write_text("a\n1\n")
        (tmp_path / "p2.csv").write_text("a\n2\n")
        fr = import_parse(str(tmp_path))
        assert fr.nrows == 2

    def test_missing_glob_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            import_parse(str(tmp_path / "nope*.csv"))


class TestPersistDispatch:
    def test_file_scheme(self, tmp_path):
        p = tmp_path / "x.csv"
        p.write_text(CSV)
        fr = parse_source(f"file://{p}")
        assert fr.nrows == 3

    def test_cloud_schemes_resolve(self):
        """s3/gs/hdfs now have real stdlib backends (frame/cloud.py,
        round 4); they resolve instead of raising."""
        for uri in ("s3://bucket/key.csv", "gs://bucket/key.csv",
                    "hdfs://nn/x.csv"):
            backend, path = resolve_persist(uri)
            assert backend.scheme == uri.split(":")[0]
            assert path == uri

    def test_unknown_scheme(self):
        with pytest.raises(ValueError, match="unknown URI scheme"):
            resolve_persist("weird://x")

    def test_http_scheme_roundtrip(self, tmp_path):
        """eager-HTTP persist against a local socket server."""
        import http.server
        import threading

        (tmp_path / "h.csv").write_text(CSV)
        handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(
            *a, directory=str(tmp_path), **kw
        )
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            url = f"http://127.0.0.1:{srv.server_port}/h.csv"
            fr = parse_source(url)
            assert fr.nrows == 3
        finally:
            srv.shutdown()

    def test_parquet_gate_names_module(self):
        import importlib.util

        if importlib.util.find_spec("pyarrow") is not None:
            pytest.skip("pyarrow available; gate not reachable")
        from h2o3_tpu.frame.ingest import parse_parquet

        with pytest.raises(ValueError, match="pyarrow"):
            parse_parquet(b"PAR1....")


class TestRestImport:
    @pytest.fixture(scope="class")
    def server(self):
        from h2o3_tpu.api import start_server

        s = start_server(port=0)
        yield s
        s.stop()

    def _req(self, server, method, path, data=None):
        body = json.dumps(data).encode() if data is not None else None
        req = urllib.request.Request(
            server.url + path, data=body,
            headers={"Content-Type": "application/json"} if body else {},
            method=method,
        )
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_import_directory_and_parse(self, server, tmp_path):
        (tmp_path / "a.csv").write_text("x,y\n1,2\n")
        (tmp_path / "b.csv").write_text("x,y\n3,4\n")
        st, out = self._req(server, "POST", "/3/ImportFiles",
                            {"path": str(tmp_path)})
        assert st == 200, out
        assert len(out["destination_frames"]) == 2
        st, out = self._req(server, "POST", "/3/Parse",
                            {"source_frames": out["destination_frames"],
                             "destination_frame": "multi"})
        assert st == 200, out
        st, fr = self._req(server, "GET", "/3/Frames/multi")
        assert st == 200
        assert fr["frames"][0]["rows"] == 2

    def test_import_svmlight_over_rest(self, server, tmp_path):
        (tmp_path / "d.svm").write_text(SVM)
        st, out = self._req(server, "POST", "/3/ImportFiles",
                            {"path": str(tmp_path / "d.svm")})
        assert st == 200
        st, setup = self._req(server, "POST", "/3/ParseSetup",
                              {"source_frames": out["destination_frames"]})
        assert st == 200 and setup["parse_type"] == "SVMLIGHT"
        st, out = self._req(server, "POST", "/3/Parse",
                            {"source_frames": out["destination_frames"],
                             "destination_frame": "svm1"})
        assert st == 200, out
        st, fr = self._req(server, "GET", "/3/Frames/svm1")
        assert fr["frames"][0]["rows"] == 3

    def test_import_gzip_arff_over_rest(self, server, tmp_path):
        (tmp_path / "w.arff.gz").write_bytes(gzip.compress(ARFF.encode()))
        st, out = self._req(server, "POST", "/3/ImportFiles",
                            {"path": str(tmp_path / "w.arff.gz")})
        assert st == 200
        st, out = self._req(server, "POST", "/3/Parse",
                            {"source_frames": out["destination_frames"],
                             "destination_frame": "arff1"})
        assert st == 200, out
        st, fr = self._req(server, "GET", "/3/Frames/arff1")
        assert fr["frames"][0]["rows"] == 3


class TestReviewFollowups:
    def test_multi_entry_zip_rbinds_parts(self, tmp_path):
        """each zip entry parses separately (headers never embed mid-data)."""
        p = tmp_path / "multi.zip"
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("a.csv", "x,y\n1,2\n")
            z.writestr("b.csv", "x,y\n3,4\n")
        fr = parse_source(str(p))
        assert fr.nrows == 2
        assert fr.col("x").type is ColType.NUM
        np.testing.assert_array_equal(sorted(fr.col("x").data), [1.0, 3.0])

    def test_svmlight_differing_widths_unify(self, tmp_path):
        (tmp_path / "a.svm").write_text("1 1:1.0 4:4.0\n")
        (tmp_path / "b.svm").write_text("0 2:2.0\n")
        fr = import_parse(str(tmp_path / "*.svm"))
        assert fr.nrows == 2
        assert fr.names == ["target", "C1", "C2", "C3", "C4"]
        # the narrow file's absent high columns are 0 (sparse semantics)
        np.testing.assert_array_equal(sorted(fr.col("C4").data), [0.0, 4.0])

    def test_columns_fast_path_bad_numeric_is_na(self, tmp_path):
        """mojo batch (column) scoring treats non-numeric as NA like the
        row path, instead of raising."""
        from h2o3_tpu import Frame
        from h2o3_tpu.genmodel import load_mojo
        from h2o3_tpu.models.mojo_export import write_mojo
        from h2o3_tpu.models.tree import GBM

        rng = np.random.default_rng(1)
        x = rng.normal(size=300)
        fr = Frame.from_dict({"x": x, "y": 2 * x})
        m = GBM(response_column="y", ntrees=3, max_depth=2, seed=1,
                min_rows=5.0).train(fr)
        path = str(tmp_path / "m.mojo")
        write_mojo(m, path)
        mm = load_mojo(path)
        got = mm.score({"x": ["1.0", "abc", None]})
        want = mm.score([{"x": "1.0"}, {"x": "abc"}, {"x": None}])
        np.testing.assert_allclose(got, want)
