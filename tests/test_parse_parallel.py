"""Chunk-parallel two-phase CSV parse: boundary correctness + reduce parity.

Reference: ParseDataset.java:623 — chunk the byte stream, tokenize chunks in
parallel, unify categorical dictionaries in a reduce (Categorical.java).
The contract pinned here: the parallel Frame is BIT-IDENTICAL (data, domains,
types, NA positions) to ``H2O3_TPU_PARSE_WORKERS=1`` and to the serial
whole-text path, for any chunk size — including chunks that cut inside
quoted newlines, chunks smaller than one record, and NA/TIME/UUID runs
split across chunks.
"""

import gzip

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType
from h2o3_tpu.frame.parse import parse_csv
from h2o3_tpu.util import telemetry


def assert_frames_identical(a, b, tag=""):
    assert a.names == b.names, tag
    assert a.nrows == b.nrows, tag
    for n in a.names:
        ca, cb = a.col(n), b.col(n)
        assert ca.type == cb.type, (tag, n, ca.type, cb.type)
        assert ca.domain == cb.domain, (tag, n)
        np.testing.assert_array_equal(ca.isna(), cb.isna(), err_msg=f"{tag}:{n}:na")
        if ca.data.dtype == object:
            assert list(ca.data) == list(cb.data), (tag, n)
        else:
            np.testing.assert_array_equal(ca.data, cb.data, err_msg=f"{tag}:{n}")


def parallel(monkeypatch, text, chunk_bytes, workers, **kw):
    monkeypatch.setenv("H2O3_TPU_PARSE_CHUNK_BYTES", str(chunk_bytes))
    monkeypatch.setenv("H2O3_TPU_PARSE_WORKERS", str(workers))
    try:
        return parse_csv(text, **kw)
    finally:
        monkeypatch.delenv("H2O3_TPU_PARSE_CHUNK_BYTES")
        monkeypatch.delenv("H2O3_TPU_PARSE_WORKERS")


def _mixed_csv(n=300):
    """Deterministic NUM/CAT/TIME/UUID/STR/NUM mix with NA runs."""
    rows = ["num,cat,time,uuid,str,count"]
    for i in range(n):
        num = "NA" if i % 11 == 0 else f"{i * 0.75 - 17:.4f}"
        cat = ["alpha", "beta", "gamma", "NA", "delta"][i % 5]
        tim = (
            "?"
            if i % 13 == 0
            else f"2021-{(i % 12) + 1:02d}-{(i % 27) + 1:02d} 10:{i % 60:02d}:{(i * 7) % 60:02d}.{i % 1000:03d}"
        )
        uid = (
            ""
            if i % 17 == 0
            else f"{i:08x}-aaaa-bbbb-cccc-{i * 31:012x}"
        )
        s = "null" if i % 19 == 0 else f"free text {i}"
        rows.append(f"{num},{cat},{tim},{uid},{s},{i}")
    return "\n".join(rows) + "\n"


class TestChunkBoundaries:
    def test_identical_across_workers_and_chunk_sizes(self, monkeypatch):
        text = _mixed_csv()
        serial = parse_csv(text)
        assert [c.type for c in serial.columns] == [
            ColType.NUM, ColType.CAT, ColType.TIME, ColType.UUID,
            ColType.STR, ColType.NUM,
        ]
        base = parallel(monkeypatch, text, 256, 1)
        assert_frames_identical(serial, base, "serial-vs-w1")
        for chunk in (64, 256, 4096):
            for w in (2, 8):
                par = parallel(monkeypatch, text, chunk, w)
                assert_frames_identical(base, par, f"c{chunk}w{w}")

    def test_quoted_newlines_span_chunk_splits(self, monkeypatch):
        rows = ["label,value"]
        for i in range(200):
            rows.append(f'"line one\nline two, {i}\n""quoted"" end",{i}')
        text = "\n".join(rows) + "\n"
        serial = parse_csv(text)
        assert serial.nrows == 200
        lab = serial.col("label")  # 200 uniques -> CAT; check via domain
        assert lab.domain[lab.data[5]] == 'line one\nline two, 5\n"quoted" end'
        for chunk in (64, 173, 1024):
            par = parallel(monkeypatch, text, chunk, 4)
            assert_frames_identical(serial, par, f"quoted-c{chunk}")

    def test_chunk_smaller_than_one_record(self, monkeypatch):
        # single records far larger than the chunk size: the chunker must
        # grow the chunk, never cut mid-record
        wide = ",".join(f"{i}.5" for i in range(200))
        long_q = '"' + "x" * 500 + '",' + ",".join("1" * 199)
        text = "a" + ",".join(f"c{i}" for i in range(1, 200)) + "\n"
        text += wide + "\n" + long_q + "\n" + wide + "\n"
        serial = parse_csv(text)
        par = parallel(monkeypatch, text, 64, 3)
        assert_frames_identical(serial, par, "monster-record")

    def test_na_and_time_and_uuid_split_across_chunks(self, monkeypatch):
        # NA runs positioned to straddle every 64-byte cut
        rows = ["t,u,x"]
        for i in range(120):
            t = "NA" if 40 <= i < 80 else f"2020-06-{(i % 28) + 1:02d}"
            u = "N/A" if 30 <= i < 90 else f"{i:08x}-1111-2222-3333-aaaaaaaaaaaa"
            rows.append(f"{t},{u},{i}")
        text = "\n".join(rows) + "\n"
        serial = parse_csv(text)
        assert serial.col("t").type is ColType.TIME
        assert serial.col("u").type is ColType.UUID
        assert int(serial.col("t").isna().sum()) == 40
        par = parallel(monkeypatch, text, 64, 8)
        assert_frames_identical(serial, par, "na-time-uuid")

    def test_categorical_dictionary_merge_is_global_sorted(self, monkeypatch):
        # chunk-local dictionaries see disjoint level subsets in different
        # first-appearance orders; the reduce must still produce one sorted
        # global domain with stable codes
        rows = ["g,x"]
        levels = [f"lv{j:02d}" for j in range(20)]
        for i in range(200):
            rows.append(f"{levels[(i * 7) % 20]},{i}")
        text = "\n".join(rows) + "\n"
        serial = parse_csv(text)
        assert serial.col("g").domain == sorted(levels)
        par = parallel(monkeypatch, text, 64, 4)
        assert_frames_identical(serial, par, "dict-merge")

    def test_crlf_and_blank_lines(self, monkeypatch):
        body = "".join(
            (f"{i}.25,tok{i % 3}\r\n" if i % 9 else f"{i}.25,tok{i % 3}\r\n\r\n")
            for i in range(150)
        )
        text = "a,b\r\n" + body
        serial = parse_csv(text)
        assert serial.nrows == 150  # blank CRLF lines dropped
        par = parallel(monkeypatch, text, 128, 4)
        assert_frames_identical(serial, par, "crlf")

    def test_mixed_native_and_python_chunks(self, monkeypatch):
        # unicode rows force individual chunks onto the python tokenizer
        # while ascii chunks stay native — the reduce must not care
        rows = ["w,x"]
        for i in range(300):
            rows.append((f"héllo-{i}" if i % 50 == 0 else f"word-{i}") + f",{i}")
        text = "\n".join(rows) + "\n"
        serial = parse_csv(text)
        par = parallel(monkeypatch, text, 96, 4)
        assert_frames_identical(serial, par, "mixed-chunks")

    def test_lone_cr_terminators_fall_back_to_serial(self, monkeypatch):
        # old-Mac lone-\r record terminators: the \n chunker cannot cut
        # these, so the pipeline must divert to the serial oracle instead
        # of swallowing the whole input as "the header"
        text = "a,b\r" + "".join(f"{i}.5,{i * 2}\r" for i in range(100))
        serial = parse_csv(text)
        assert serial.nrows == 100
        par = parallel(monkeypatch, text, 64, 4)
        assert_frames_identical(serial, par, "lone-cr")

    def test_formfeed_blank_line_before_header(self, monkeypatch):
        # "\f" is blank to python's r.strip() but not to the chunker's
        # header scan — divergent byte, must take the serial oracle
        text = "\f\na,b\n" + "1,2\n" * 50
        serial = parse_csv(text)
        par = parallel(monkeypatch, text, 64, 2)
        assert_frames_identical(serial, par, "formfeed")

    def test_mid_stream_vertical_tab_with_quotes_elsewhere(self, monkeypatch):
        # a \v appears far into the body while quotes exist in EARLIER
        # chunks: the serial path's quote state machine keeps \v inline,
        # so the recovered tail must be split with machine semantics even
        # though the tail itself is quote-free
        rows = ["a,b"] + [f'"q{i}",{i}' for i in range(40)]
        rows += [f"plain\v{i},{i}" for i in range(40, 80)]
        text = "\n".join(rows) + "\n"
        serial = parse_csv(text)
        assert serial.nrows == 80  # \v never terminates a record here
        par = parallel(monkeypatch, text, 64, 4)
        assert_frames_identical(serial, par, "vt-after-quotes")

    def test_mid_stream_vertical_tab_no_quotes(self, monkeypatch):
        # same divergent byte, quote-free input: serial splitlines DOES
        # split on \v — the recovered tail must too
        rows = ["a,b"] + [f"p{i},{i}" for i in range(40)]
        rows += [f"x{i}\vy{i},{i}" for i in range(40, 60)]
        text = "\n".join(rows) + "\n"
        serial = parse_csv(text)
        assert serial.nrows > 60  # the \v splits records
        par = parallel(monkeypatch, text, 64, 4)
        assert_frames_identical(serial, par, "vt-no-quotes")

    def test_first_record_larger_than_sample_window(self, monkeypatch):
        # a quoted first cell bigger than the 1 MiB setup-sampling window:
        # no complete record fits the sample, so the stream impl must
        # drain and take the serial path instead of raising 'empty input'
        big = "line\n" * 250_000  # ~1.25 MB of quoted newlines
        text = f'"{big}",1\n"tail",2\n'
        par = parallel(monkeypatch, text, 256, 2)
        assert par.nrows == 2
        serial = parse_csv(text)
        assert_frames_identical(serial, par, "giant-first-record")

    def test_cyrillic_text_keeps_pipeline_engaged(self, monkeypatch):
        # 0x85 appears as the utf-8 continuation byte of ordinary
        # characters (Cyrillic 'х' = D1 85): that must NOT be mistaken
        # for a NEL terminator and silently disable the pipeline
        chunks = telemetry.REGISTRY.get("parse_chunks_total")
        rows = ["word,x"] + [f"хлеб{i % 7},{i}" for i in range(300)]
        text = "\n".join(rows) + "\n"
        serial = parse_csv(text)
        c0 = chunks.total()
        par = parallel(monkeypatch, text, 128, 4)
        assert chunks.total() > c0  # chunk pipeline actually ran
        assert_frames_identical(serial, par, "cyrillic")

    def test_real_nel_terminator_diverts(self, monkeypatch):
        # an actual U+0085 NEL splits records in python's splitlines:
        # the pipeline must divert and stay bit-identical
        text = "a,b\n" + "1,2\x853,4\n" * 30
        serial = parse_csv(text)
        par = parallel(monkeypatch, text, 64, 2)
        assert_frames_identical(serial, par, "nel")

    def test_header_only_and_blank_prefix(self, monkeypatch):
        text = "\n  \n a,b\n" + "1,2\n" * 40
        serial = parse_csv(text)
        par = parallel(monkeypatch, text, 64, 2)
        assert_frames_identical(serial, par, "blank-prefix")
        assert par.names == ["a", "b"]


class TestStreamedDecompression:
    def test_gz_stream_matches_plain(self, monkeypatch, tmp_path):
        from h2o3_tpu.frame.ingest import parse_bytes

        text = _mixed_csv(200)
        plain = parse_csv(text)
        monkeypatch.setenv("H2O3_TPU_PARSE_CHUNK_BYTES", "256")
        fr = parse_bytes("m.csv.gz", gzip.compress(text.encode()))
        assert_frames_identical(plain, fr, "gz")

    def test_zip_entry_streams(self, monkeypatch, tmp_path):
        import zipfile as _zf
        import io as _io

        from h2o3_tpu.frame.ingest import parse_bytes

        text = _mixed_csv(150)
        buf = _io.BytesIO()
        with _zf.ZipFile(buf, "w", _zf.ZIP_DEFLATED) as z:
            z.writestr("part.csv", text)
        monkeypatch.setenv("H2O3_TPU_PARSE_CHUNK_BYTES", "256")
        fr = parse_bytes("m.zip", buf.getvalue())
        assert_frames_identical(parse_csv(text), fr, "zip")


class TestTelemetry:
    def test_chunk_and_worker_meters(self, monkeypatch):
        chunks = telemetry.REGISTRY.get("parse_chunks_total")
        rows = telemetry.REGISTRY.get("parse_rows_total")
        c0 = chunks.total()
        text = _mixed_csv(200)
        parallel(monkeypatch, text, 256, 3)
        assert chunks.total() > c0  # several chunks tokenized
        assert telemetry.REGISTRY.get("parse_workers").value() == 3.0
        parallel_rows = sum(
            s["value"]
            for s in rows.snapshot()["series"]
            if s["labels"]["parser"].endswith("-parallel")
        )
        assert parallel_rows >= 200
