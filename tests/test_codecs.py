"""Chunk codec layer (h2o3_tpu/frame/codecs.py).

The contract under test: a codec is selected for a column-chunk only if
a literal encode→decode round-trip reproduces the dense payload
bit-exactly (uint64 views), so decoding never changes a result anywhere
— NaN payload bits, signed zeros, denormals and int-boundary floats
either survive exactly or the chunk stays dense.  Group homogenization
(group_rep) and codec-aware rollups (payload_rollups) must uphold the
same contract, and a chunk-homed parse with codecs enabled must
materialize bit-identically to the same parse with H2O3_TPU_CODECS=0.
"""

import numpy as np
import pytest

from h2o3_tpu.frame import codecs
from h2o3_tpu.frame.frame import NA_CAT, ColType, Column
from h2o3_tpu.frame.parse import parse_csv
from h2o3_tpu.frame.rollups import compute_rollups, payload_rollups
from h2o3_tpu.util import telemetry

DENORM = 5e-324  # smallest positive subnormal


def _bits(x):
    return np.ascontiguousarray(np.asarray(x, dtype=np.float64)).view(
        np.uint64)


def _enc(x):
    """Encoded payload of one numeric column-chunk."""
    x = np.asarray(x, dtype=np.float64)
    return codecs.encode_chunk([int(x.size), [x], False])[1][0]


def _codec_of(payload):
    return payload["c"] if codecs.is_encoded(payload) else "dense"


def _rng(seed=7):
    return np.random.default_rng(seed)


# ---------------------------------------------------------------------------
# property-style special-value matrix: encode→decode is uint64-identical
# (or the chunk legitimately stayed dense, which is identity for free)

SPECIALS = {
    "all_nan": np.full(64, np.nan),
    "all_pos_zero": np.zeros(64),
    "all_neg_zero": np.full(64, -0.0),
    "signed_zero_mix": np.where(np.arange(64) % 2 == 0, 0.0, -0.0),
    "const_pi": np.full(100, np.pi),
    "single_value": np.array([42.0]),
    "single_nan": np.array([np.nan]),
    "denormals": np.array([DENORM, -DENORM, 0.0, -0.0, 2 * DENORM] * 8),
    "inf_mix": np.array([np.inf, -np.inf, 0.0, 1.5, np.nan] * 10),
    "int_boundary": np.array(
        [2.0**53, 2.0**53 - 1, -(2.0**53), 2.0**31, -(2.0**31) - 1] * 5),
    "small_ints_with_na": np.where(
        np.arange(200) % 13 == 0, np.nan, np.arange(200) % 97),
    "quarter_steps": np.arange(300) * 0.25 - 20.0,
    "mostly_zero": np.where(np.arange(500) % 83 == 0, 3.75, 0.0),
    "few_uniq_irrational": _rng().choice(
        [np.pi, np.e, np.sqrt(2), -np.pi / 3, 1 / 3], size=400),
    "f32_exact": _rng(3).standard_normal(300).astype(
        np.float32).astype(np.float64),
    "random_dense": _rng(5).standard_normal(256),
    "huge_magnitudes": np.array([1e300, -1e300, 1e-300, -1e-300] * 8),
    "empty": np.empty(0),
}


@pytest.mark.parametrize("name", sorted(SPECIALS))
def test_roundtrip_bit_identity(name):
    x = np.asarray(SPECIALS[name], dtype=np.float64)
    value = codecs.encode_chunk([int(x.size), [x.copy()], False])
    back = codecs.decode_chunk(value)[1][0]
    back = np.asarray(back, dtype=np.float64)
    assert back.shape == x.shape
    assert np.array_equal(_bits(back), _bits(x)), name


def test_selection_picks_expected_codecs():
    assert _codec_of(_enc(np.full(512, 7.5))) == "const"
    assert _codec_of(_enc(np.full(512, np.nan))) == "const"
    assert _codec_of(_enc(SPECIALS["mostly_zero"])) == "sparse"
    assert _codec_of(_enc(SPECIALS["small_ints_with_na"])) == "affine"
    assert _codec_of(_enc(SPECIALS["quarter_steps"])) == "affine"
    assert _codec_of(_enc(SPECIALS["few_uniq_irrational"])) == "dict"
    assert _codec_of(_enc(_rng(3).standard_normal(4096).astype(
        np.float32).astype(np.float64))) == "f32"
    # all-unique random f64: no candidate beats dense
    assert _codec_of(_enc(_rng(5).standard_normal(4096))) == "dense"


def test_affine_na_sentinel_is_reserved():
    p = _enc(SPECIALS["small_ints_with_na"])
    assert p["c"] == "affine"
    sent = int(np.iinfo(p["codes"].dtype).max)
    na_rows = np.isnan(SPECIALS["small_ints_with_na"])
    assert np.array_equal(p["codes"] == sent, na_rows)
    # a domain that needs the all-ones code cannot pack into that dtype
    full = np.arange(256, dtype=np.float64)  # 0..255 needs code 255
    pf = _enc(full)
    if codecs.is_encoded(pf) and pf["c"] == "affine":
        assert pf["codes"].dtype == np.uint16


def test_encode_is_idempotent_and_metered():
    c = telemetry.REGISTRY.get("chunk_codec_total")
    before = float(c.value(codec="const"))
    x = np.full(128, 2.5)
    v1 = codecs.encode_chunk([128, [x], False])
    assert float(c.value(codec="const")) == before + 1
    v2 = codecs.encode_chunk(v1)  # already encoded: pass-through, unmetered
    assert v2[1][0] is v1[1][0]
    assert float(c.value(codec="const")) == before + 1
    g = telemetry.REGISTRY.get("chunk_resident_bytes")
    assert float(g.value(codec="const")) > 0


def test_kill_switch_lands_dense(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_CODECS", "0")
    v = codecs.encode_chunk([128, [np.full(128, 2.5)], False])
    assert not codecs.is_encoded_chunk(v)
    assert isinstance(v[1][0], np.ndarray)


def test_min_ratio_rejects_marginal_wins(monkeypatch):
    x = _rng(3).standard_normal(512).astype(np.float32).astype(np.float64)
    assert _codec_of(_enc(x)) == "f32"  # 0.5x dense, under the default 0.75
    monkeypatch.setenv("H2O3_TPU_CODEC_MIN_RATIO", "0.4")
    assert _codec_of(_enc(x)) == "dense"


def test_encoded_nbytes_reports_packed_size():
    x = np.where(np.arange(4096) % 83 == 0, 3.75, 0.0)
    enc = codecs.encode_chunk([x.size, [x.copy()], False])
    dense = [x.size, [x], False]
    assert codecs.encoded_nbytes(enc) < 0.1 * codecs.encoded_nbytes(dense)


def test_cat_roundtrip_long_domain():
    n, levels = 1000, 300
    codes = (np.arange(n) % levels).astype(np.int32)
    codes[::37] = NA_CAT
    domain = [f"lv{i:04d}" for i in range(levels)]
    v = codecs.encode_chunk([n, [(codes.copy(), list(domain))], False])
    p = v[1][0]
    assert codecs.is_encoded(p) and p["c"] == "catpack"
    assert p["codes"].dtype == np.uint16  # 300 levels outgrow uint8
    back_codes, back_domain = codecs.decode_column(p)
    assert np.array_equal(back_codes, codes)
    assert back_domain == domain


def test_str_roundtrip_dictionary():
    vals = ["alpha", "beta", "gamma", None, "alpha"] * 200
    arr = np.empty(len(vals), dtype=object)
    arr[:] = vals
    v = codecs.encode_chunk([arr.size, [arr], False])
    p = v[1][0]
    assert codecs.is_encoded(p) and p["c"] == "strdict"
    back = codecs.decode_column(p)
    assert all(a == b for a, b in zip(back, arr))


# ---------------------------------------------------------------------------
# group homogenization: regrouping must re-verify the chunk contract


def _group_case(chunks):
    payloads = [_enc(c) for c in chunks]
    full = np.concatenate([np.asarray(c, dtype=np.float64) for c in chunks])
    return payloads, full


def _rep_decode(rep):
    kind = rep[0]
    if kind == "const":
        return np.repeat(rep[1], rep[2])
    if kind == "affine":
        codes, off, scale, sent = rep[1], rep[2], rep[3], rep[4]
        out = off + codes.astype(np.float64) * scale
        out[codes == sent] = np.nan
        return out
    if kind == "dict":
        return rep[2][rep[1]]
    if kind == "f32":
        return np.asarray(rep[1]).astype(np.float64)
    return np.asarray(rep[1], dtype=np.float64)


GROUP_CASES = {
    "all_const": [np.full(50, 1.25), np.full(70, 1.25)],
    "const_mismatch": [np.full(50, 1.25), np.full(70, 2.5)],
    "affine_shared_scale": [np.arange(100, 150, dtype=np.float64),
                            np.arange(400, 420, dtype=np.float64)],
    "affine_with_na": [
        np.where(np.arange(120) % 11 == 0, np.nan,
                 np.arange(120, dtype=np.float64)),
        np.arange(60, dtype=np.float64) + 500.0],
    "affine_mixed_scale": [np.arange(80) * 0.5, np.arange(80) * 0.25],
    "all_f32": [_rng(1).standard_normal(90).astype(np.float32).astype(
        np.float64), _rng(2).standard_normal(40).astype(
        np.float32).astype(np.float64)],
    "mixed_enc_dense": [np.full(50, 3.0), _rng(9).standard_normal(128)],
    "sparse_plus_const": [np.where(np.arange(400) % 97 == 0, 2.0, 0.0),
                          np.zeros(100)],
}


@pytest.mark.parametrize("name", sorted(GROUP_CASES))
def test_group_rep_bit_identity(name):
    payloads, full = _group_case(GROUP_CASES[name])
    rep = codecs.group_rep(payloads)
    back = _rep_decode(rep)
    assert back.shape == full.shape
    assert np.array_equal(_bits(back), _bits(full)), (name, rep[0])


def test_group_rep_shapes():
    payloads, _ = _group_case(GROUP_CASES["all_const"])
    assert codecs.group_rep(payloads)[0] == "const"
    payloads, _ = _group_case(GROUP_CASES["affine_shared_scale"])
    assert codecs.group_rep(payloads)[0] == "affine"
    payloads, _ = _group_case(GROUP_CASES["all_f32"])
    assert codecs.group_rep(payloads)[0] == "f32"
    payloads, _ = _group_case(GROUP_CASES["mixed_enc_dense"])
    assert codecs.group_rep(payloads)[0] == "dense"
    # heterogeneous affine scales fall through to the dict union
    payloads, _ = _group_case(GROUP_CASES["affine_mixed_scale"])
    assert codecs.group_rep(payloads)[0] in ("dict", "dense")


def test_group_rep_device_parity_affine():
    """The fused program's decode (offset + code*scale as two f64 ops,
    sentinel → NaN) matches the host decode bit-for-bit on device."""
    import jax
    import jax.numpy as jnp

    payloads, full = _group_case(GROUP_CASES["affine_with_na"])
    rep = codecs.group_rep(payloads)
    assert rep[0] == "affine"
    _, codes, off, scale, sent = (rep[0], rep[1], rep[2], rep[3], rep[4])
    with jax.experimental.enable_x64():
        x = jnp.asarray(off) + jnp.asarray(codes).astype(
            jnp.float64) * jnp.asarray(scale)
        dev = np.asarray(
            jnp.where(jnp.asarray(codes) == sent, jnp.nan, x))
    assert np.array_equal(_bits(dev), _bits(full))


# ---------------------------------------------------------------------------
# codec-aware rollups: exact where promised, moment-merge where streamed


ROLLUP_CASES = {
    "mixed_codecs": [np.full(64, 4.0),
                     np.where(np.arange(300) % 83 == 0, 3.75, 0.0),
                     np.where(np.arange(200) % 13 == 0, np.nan,
                              np.arange(200) % 97),
                     _rng(4).standard_normal(150)],
    "all_na": [np.full(30, np.nan), np.full(20, np.nan)],
    "single_chunk_int": [np.arange(500, dtype=np.float64)],
    "with_inf": [np.array([np.inf, -np.inf, 1.0, np.nan] * 25)],
}


@pytest.mark.parametrize("name", sorted(ROLLUP_CASES))
def test_payload_rollups_matches_dense(name):
    chunks = ROLLUP_CASES[name]
    payloads = [_enc(c) for c in chunks]
    full = np.concatenate([np.asarray(c, dtype=np.float64) for c in chunks])
    got = payload_rollups(payloads)
    ref = compute_rollups(Column("x", full.copy(), ColType.NUM))
    # exact fields
    assert got.na_count == ref.na_count
    assert got.zero_count == ref.zero_count
    assert got.is_int == ref.is_int
    assert np.array_equal(_bits([got.min]), _bits([ref.min]))
    assert np.array_equal(_bits([got.max]), _bits([ref.max]))
    # streamed moments: merged per-chunk, final-ulp tolerance only
    if np.isnan(ref.mean):
        assert np.isnan(got.mean)
    else:
        np.testing.assert_allclose(got.mean, ref.mean, rtol=1e-12, atol=0)
        np.testing.assert_allclose(got.sigma, ref.sigma, rtol=1e-9,
                                   atol=1e-300)


# ---------------------------------------------------------------------------
# cluster: a chunk-homed parse with codecs on materializes bit-identically
# to the same parse with H2O3_TPU_CODECS=0 and to the serial parser


def _mixed_csv(n=3000):
    rng = np.random.default_rng(17)
    dense = rng.standard_normal(n)
    lines = ["ints,const,sparse,dense,cat"]
    for i in range(n):
        iv = "" if i % 13 == 0 else str(i % 97)
        sv = "3.75" if i % 83 == 0 else "0"
        lines.append(
            f"{iv},7.5,{sv},{dense[i]!r},lv{i % 5}")
    return "\n".join(lines) + "\n"


@pytest.mark.leaks_keys
def test_cluster_encoded_vs_dense_bit_identity(monkeypatch):
    from test_rapids_dist import _form_cloud, _parse_to_homes, _stop_all

    from h2o3_tpu.cluster.frames import chunk_key
    from h2o3_tpu.cluster.membership import set_local_cloud

    text = _mixed_csv()
    serial = parse_csv(text)
    clouds = _form_cloud(2, "cdx")
    set_local_cloud(clouds[0])
    try:
        enc = _parse_to_homes(clouds[0], "codec_parity_enc", text,
                              chunk_bytes=16384)
        g0 = enc.chunk_layout["groups"][0]
        v0 = clouds[0].dkv_store.get(chunk_key(g0["anchor"], int(g0["lo"])))
        assert codecs.is_encoded_chunk(v0), "parse landed dense payloads"
        assert enc.nbytes_wire > 0

        monkeypatch.setenv("H2O3_TPU_CODECS", "0")
        plain = _parse_to_homes(clouds[0], "codec_parity_plain", text,
                                chunk_bytes=16384)
        monkeypatch.delenv("H2O3_TPU_CODECS")
        # encoded replicas are smaller than dense ones for this mix
        assert enc.nbytes_wire < plain.nbytes_wire

        for name in serial.names:
            ref = serial.col(name)
            a, b = enc.col(name), plain.col(name)
            if ref.type in (ColType.STR, ColType.UUID):
                continue
            assert np.array_equal(_bits(a.numeric_view()),
                                  _bits(ref.numeric_view())), name
            assert np.array_equal(_bits(a.numeric_view()),
                                  _bits(b.numeric_view())), name
            if ref.type is ColType.CAT:
                assert a.domain == ref.domain

        # unmaterialized rollups off encoded payloads: exact fields agree
        enc2 = _parse_to_homes(clouds[0], "codec_parity_enc2", text,
                               chunk_bytes=16384)
        r = enc2.column_rollups("ints")
        rr = serial.col("ints").rollups
        assert (r.na_count, r.zero_count, r.min, r.max) == \
            (rr.na_count, rr.zero_count, rr.min, rr.max)
    finally:
        set_local_cloud(None)
        _stop_all(clouds)
