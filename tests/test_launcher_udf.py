"""CLI launcher (water/H2O.java OptArgs + H2OApp), Lockable, and UDF
custom metrics (water/udf)."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _frame(rng, n=300):
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] + 0.3 * rng.normal(size=n) > 0).astype(np.int32)
    cols = [Column(f"x{i}", X[:, i]) for i in range(3)]
    cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
    return Frame(cols)


class TestLauncher:
    def test_python_dash_m_starts_a_node(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        proc = subprocess.Popen(
            [sys.executable, "-m", "h2o3_tpu", "--port", "0",
             "--name", "launcher-test", "--log-dir", str(tmp_path)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            line = ""
            deadline = time.time() + 120
            while time.time() < deadline:
                line = proc.stdout.readline()
                if "up at http" in line:
                    break
            assert "up at http" in line, line
            url = line.strip().rsplit(" ", 1)[-1]
            with urllib.request.urlopen(url + "/3/Cloud") as resp:
                cloud = json.loads(resp.read())
            assert cloud["cloud_name"] == "launcher-test"
            with urllib.request.urlopen(url + "/3/Ping") as resp:
                assert json.loads(resp.read())["ok"]
            # graceful shutdown on SIGTERM
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()

    def test_parse_mem(self):
        from h2o3_tpu.__main__ import _parse_mem

        assert _parse_mem("4g") == 4 << 30
        assert _parse_mem("512m") == 512 << 20
        assert _parse_mem("1024") == 1024


class TestLockable:
    def test_training_frame_cannot_be_deleted_mid_build(self, rng):
        """water/Lockable.java: a frame read-locked by a training job
        refuses deletion until the job finishes."""
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.glm import GLM

        fr = _frame(rng)
        fr.key = "lockable_fr"
        DKV.put(fr.key, fr)

        observed = {}
        from h2o3_tpu.models import glm as glm_mod

        orig_fit = GLM._fit

        def snooping_fit(self, frame, valid=None):
            # mid-build: deletion must raise
            try:
                DKV.remove("lockable_fr")
                observed["deleted"] = True
            except ValueError as e:
                observed["error"] = str(e)
            return orig_fit(self, frame, valid)

        GLM._fit = snooping_fit
        try:
            GLM(response_column="y", family="binomial").train(fr)
        finally:
            GLM._fit = orig_fit

        assert "deleted" not in observed
        assert "locked" in observed["error"]
        # after training the lock is released
        DKV.remove("lockable_fr")
        assert DKV.get("lockable_fr") is None


class TestCustomMetricUDF:
    def test_in_process_callable(self, rng):
        from h2o3_tpu.models.glm import GLM
        from h2o3_tpu.udf import custom_metric

        fr = _frame(rng)
        m = GLM(response_column="y", family="binomial").train(fr)

        def brier(actual, predicted):
            return float(np.mean((actual - predicted) ** 2))

        v = custom_metric(m, fr, brier)
        assert 0.0 <= v <= 0.3

    def test_upload_gated_and_enabled(self, rng, monkeypatch):
        from h2o3_tpu import udf

        src = "def metric(actual, predicted):\n    return float(abs(actual - predicted).mean())\n"
        monkeypatch.delenv("H2O3_TPU_ENABLE_UDF", raising=False)
        with pytest.raises(PermissionError):
            udf.compile_metric("mae_udf", src)
        monkeypatch.setenv("H2O3_TPU_ENABLE_UDF", "1")
        udf.compile_metric("mae_udf", src)

        from h2o3_tpu.models.glm import GLM
        from h2o3_tpu.udf import custom_metric

        fr = _frame(rng)
        m = GLM(response_column="y", family="binomial").train(fr)
        v = custom_metric(m, fr, "mae_udf")
        assert 0.0 <= v <= 1.0

    def test_udf_over_rest(self, rng, monkeypatch):
        from h2o3_tpu.api import start_server
        from h2o3_tpu.keyed import DKV

        monkeypatch.setenv("H2O3_TPU_ENABLE_UDF", "1")
        fr = _frame(rng)
        fr.key = "udf_fr"
        DKV.put(fr.key, fr)
        from h2o3_tpu.models.glm import GLM

        m = GLM(response_column="y", family="binomial").train(fr)

        s = start_server(port=0)
        try:
            def post(path, payload):
                req = urllib.request.Request(
                    s.url + path, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req) as resp:
                    return json.loads(resp.read())

            post("/3/CustomMetric", {
                "name": "acc",
                "source": "def metric(actual, predicted):\n"
                          "    return float(((predicted > 0.5) == actual).mean())\n",
            })
            out = post("/3/CustomMetric/eval", {
                "model_id": m.key, "frame_id": "udf_fr", "name": "acc",
            })
            assert 0.5 <= out["value"] <= 1.0
        finally:
            s.stop()
            DKV.remove("udf_fr")


class TestPodLaunch:
    """--coordinator multi-host flags (the h2odriver / h2o-k8s analogue)."""

    def test_coordinator_requires_pod_shape(self, capsys):
        from h2o3_tpu.__main__ import main

        rc = main(["--coordinator", "localhost:9999", "--port", "0"])
        assert rc == 2

    def test_single_process_pod_forms_and_serves(self, tmp_path):
        """A 1-process pod rendezvous at its own coordinator and serves —
        the same code path every pod member runs (k8s ordinal 0)."""
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coord = f"127.0.0.1:{s.getsockname()[1]}"
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        proc = subprocess.Popen(
            [sys.executable, "-m", "h2o3_tpu", "--port", "0",
             "--name", "pod-test", "--coordinator", coord,
             "--num-processes", "1", "--process-id", "0"],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            line, seen = "", []
            deadline = time.time() + 120
            while time.time() < deadline:
                line = proc.stdout.readline()
                if line:
                    seen.append(line)
                if "up at http" in line:
                    break
                if line == "":
                    # EOF: either the child died, or it closed stdout while
                    # still running — both mean the banner can never arrive;
                    # spinning on instant-'' reads would burn the deadline
                    break
            assert "up at http" in line, "".join(seen)
            url = line.strip().rsplit(" ", 1)[-1]
            with urllib.request.urlopen(url + "/3/Cloud") as resp:
                cloud = json.loads(resp.read())
            assert cloud["cloud_name"] == "pod-test"
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
