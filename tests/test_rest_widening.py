"""REST widening + observability — VERDICT r2 items 7 and 9.

New routes: varimp, PartialDependence, Trees inspection, Word2Vec
synonyms/transform, CreateFrame, MissingInserter, Metadata schemas,
Logs, Timeline (real ring), JStack (real stacks), WaterMeterCpuTicks.
Also: the no-silent-param guard at the REST boundary and estimator
kwargs == builder dataclass fields."""

import dataclasses
import json
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import start_server


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys

CSV = "x0,x1,cat,y\n" + "\n".join(
    f"{a:.3f},{b:.3f},{'u' if a > 0 else 'v'},{'yes' if a + b > 0 else 'no'}"
    for a, b in np.random.default_rng(7).normal(size=(400, 2))
)


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _req(server, method, path, data=None, raw=False):
    body = json.dumps(data).encode() if data is not None else None
    req = urllib.request.Request(
        server.url + path, data=body,
        headers={"Content-Type": "application/json"} if body else {},
        method=method,
    )
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def gbm(server):
    st, up = _req(server, "POST", "/3/PostFile", {"data": CSV})
    assert st == 200
    st, out = _req(server, "POST", "/3/Parse",
                   {"source_frames": [up["destination_frame"]],
                    "destination_frame": "wide_train"})
    assert st == 200, out
    st, out = _req(server, "POST", "/3/ModelBuilders/gbm",
                   {"training_frame": "wide_train", "response_column": "y",
                    "ntrees": 5, "max_depth": 3, "seed": 1, "min_rows": 5,
                    "model_id": "wide_gbm"})
    assert st == 200, out
    return "wide_gbm"


class TestModelIntrospection:
    def test_varimp(self, server, gbm):
        st, out = _req(server, "GET", f"/3/Models/{gbm}/varimp")
        assert st == 200, out
        vi = out["varimp"]
        assert vi and vi[0]["scaled_importance"] == 1.0
        assert abs(sum(v["percentage"] for v in vi) - 1.0) < 1e-6
        names = {v["variable"] for v in vi}
        assert {"x0", "x1"} <= names

    def test_partial_dependence(self, server, gbm):
        st, out = _req(server, "POST", "/3/PartialDependence",
                       {"model_id": gbm, "frame_id": "wide_train",
                        "cols": ["x0"], "nbins": 5})
        assert st == 200, out
        pd = out["partial_dependence_data"][0]
        assert pd["column"] == "x0"
        assert len(pd["values"]) == 5 and len(pd["mean_response"]) == 5
        # x0 drives y upward: mean response should increase overall
        assert pd["mean_response"][-1] > pd["mean_response"][0]

    def test_tree_inspection(self, server, gbm):
        st, out = _req(server, "GET", f"/3/Trees/{gbm}/0")
        assert st == 200, out
        assert out["tree_number"] == 0
        assert any(out["is_split"])
        # split nodes carry a feature + raw threshold
        i = out["is_split"].index(True)
        assert out["features"][i] in ("x0", "x1", "cat")
        assert out["thresholds"][i] is not None
        st, out = _req(server, "GET", f"/3/Trees/{gbm}/999")
        assert st == 404

    def test_word2vec_synonyms_and_transform(self, server):
        # one (tokenized) word per row, like the reference's words frame
        docs = (["king", "queen", "royal", "palace"] * 30
                + ["dog", "cat", "pet", "animal"] * 30)
        csv = "text\n" + "\n".join(docs)
        st, up = _req(server, "POST", "/3/PostFile", {"data": csv})
        st, out = _req(server, "POST", "/3/Parse",
                       {"source_frames": [up["destination_frame"]],
                        "destination_frame": "w2v_docs",
                        "column_types": json.dumps(["string"])})
        assert st == 200, out
        st, out = _req(server, "POST", "/3/ModelBuilders/word2vec",
                       {"training_frame": "w2v_docs", "vec_size": 8,
                        "epochs": 2, "seed": 1, "model_id": "w2v_1"})
        assert st == 200, out
        st, out = _req(server, "POST", "/3/Word2VecSynonyms",
                       {"model_id": "w2v_1", "word": "king", "count": 3})
        assert st == 200, out
        assert len(out["synonyms"]) <= 3
        st, out = _req(server, "POST", "/3/Word2VecTransform",
                       {"model_id": "w2v_1", "words_frame": "w2v_docs",
                        "aggregate_method": "average"})
        assert st == 200, out
        assert "vectors_frame" in out


class TestSyntheticData:
    def test_create_frame(self, server):
        st, out = _req(server, "POST", "/3/CreateFrame",
                       {"rows": 500, "cols": 10, "seed": 3,
                        "categorical_fraction": 0.2, "has_response": "true"})
        assert st == 200, out
        key = out["destination_frame"]["name"]
        st, fr = _req(server, "GET", f"/3/Frames/{key}")
        assert fr["frames"][0]["rows"] == 500
        assert fr["frames"][0]["num_columns"] == 11  # + response

    def test_missing_inserter(self, server):
        st, out = _req(server, "POST", "/3/CreateFrame",
                       {"rows": 400, "cols": 4, "seed": 4,
                        "dest": "mi_frame"})
        assert st == 200
        st, out = _req(server, "POST", "/3/MissingInserter",
                       {"dataset": "mi_frame", "fraction": 0.3, "seed": 5})
        assert st == 200, out
        st, fr = _req(server, "GET", "/3/Frames/mi_frame")
        missing = sum(c["missing_count"] for c in fr["frames"][0]["columns"])
        assert missing > 400 * 4 * 0.15  # ~30% +- noise


class TestSchemasMetadata:
    def test_schemas_list(self, server):
        st, out = _req(server, "GET", "/3/Metadata/schemas")
        assert st == 200
        names = {s["name"] for s in out["schemas"]}
        assert {"GBMParameters", "GLMParameters", "DRFParameters"} <= names

    def test_schema_get(self, server):
        st, out = _req(server, "GET", "/3/Metadata/schemas/GBMParameters")
        assert st == 200
        fields = {f["name"] for f in out["schemas"][0]["fields"]}
        assert {"ntrees", "learn_rate", "monotone_constraints"} <= fields


class TestObservability:
    def test_training_leaves_timeline_trace(self, server, gbm):
        """A GBM train leaves an inspectable trace over REST (VERDICT item
        9 'done' criterion)."""
        st, out = _req(server, "GET", "/3/Timeline?count=5000")
        assert st == 200
        kinds = {e["kind"] for e in out["events"]}
        assert "train" in kinds
        assert "tree_block" in kinds
        assert "rest" in kinds
        train_evts = [e for e in out["events"] if e["kind"] == "train"]
        assert any(e.get("algo") == "gbm" and e.get("ok") for e in train_evts)
        assert all("duration_ms" in e for e in train_evts)

    def test_logs_capture_training(self, server, gbm):
        st, out = _req(server, "GET", "/3/Logs")
        assert st == 200
        joined = "\n".join(out["lines"])
        assert "gbm train start" in joined
        assert "gbm train done" in joined

    def test_logs_download(self, server):
        st, raw = _req(server, "GET", "/3/Logs/download", raw=True)
        assert st == 200
        assert b"INFO" in raw

    def test_jstack_has_real_stacks(self, server):
        st, out = _req(server, "GET", "/3/JStack")
        assert st == 200
        main = [t for t in out["traces"] if t["stack"]]
        assert main, "no thread produced a stack"
        assert any("h2o3_tpu" in "".join(t["stack"]) for t in out["traces"])

    def test_watermeter(self, server):
        st, out = _req(server, "GET", "/3/WaterMeterCpuTicks")
        assert st == 200
        assert len(out["cpu_ticks"][0]) == 7

    def test_ping(self, server):
        st, out = _req(server, "GET", "/3/Ping")
        assert st == 200 and out["ok"]


class TestParamStrictness:
    def test_unknown_train_param_is_400(self, server, gbm):
        st, out = _req(server, "POST", "/3/ModelBuilders/gbm",
                       {"training_frame": "wide_train", "response_column": "y",
                        "ntreees": 5})
        assert st == 400
        assert "ntreees" in out["msg"]

    def test_route_count(self, server):
        st, out = _req(server, "GET", "/3/Metadata/endpoints")
        assert st == 200
        assert len(out["routes"]) >= 60, f"only {len(out['routes'])} routes"


class TestEstimatorSurface:
    def test_estimator_kwargs_match_builder_dataclasses(self):
        """Every estimator exposes exactly its builder's params
        (VERDICT item 7 'done' criterion)."""
        import h2o3_tpu.client.estimators as est
        from h2o3_tpu.api.registry import algo_map

        algos = algo_map()
        covered = set()
        for name in dir(est):
            cls = getattr(est, name)
            if isinstance(cls, type) and issubclass(cls, est.H2OEstimator) \
                    and cls is not est.H2OEstimator:
                _, pcls = algos[cls.algo]
                want = frozenset(f.name for f in dataclasses.fields(pcls))
                assert cls.param_names() == want, cls.algo
                covered.add(cls.algo)
        assert covered >= set(algos) - {"svd"} or covered >= set(algos), (
            sorted(set(algos) - covered)
        )

    def test_unknown_estimator_kwarg_raises(self):
        from h2o3_tpu.client.estimators import H2OGradientBoostingEstimator

        with pytest.raises(TypeError, match="ntreees"):
            H2OGradientBoostingEstimator(ntreees=5)


def test_metrics_schema_accepts_dict():
    """ADVICE r4: isolation forest stores training_metrics as a plain dict;
    the model schema must surface its entries instead of {}."""
    from h2o3_tpu.api.handlers import _metrics_schema

    out = _metrics_schema({"mean_score": 0.42, "max_score": 0.9})
    assert out == {"mean_score": 0.42, "max_score": 0.9}
    assert _metrics_schema(None) is None
