"""The health plane: flight-recorder ring semantics, watchdog rule
arithmetic, SIGUSR2 stack capture, the federated ``/3/Diagnostics``
bundle's partial-never-5xx contract, and the crash-file round trip
through ``scripts/diag_view.py``.

The ring and rule tests are pure unit checks (no cloud, no sockets);
the federation tests run two real Cloud instances over loopback behind
a live REST server — the same wiring a multi-process deployment uses.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.cluster import health
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.util import flight

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
_DIAG_VIEW = os.path.join(_ROOT, "scripts", "diag_view.py")


def _wait_for(cond, timeout=10.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


# ---------------------------------------------------------------------------
# the ring


class TestFlightRing:
    def test_bounded_with_overwrite_order(self):
        r = flight.FlightRecorder(capacity=8)
        for i in range(20):
            r.record(flight.RPC, "info", "ev", i=i)
        snap = r.snapshot()
        # exactly capacity events survive, the OLDEST were overwritten,
        # and what remains is oldest-first
        assert [e["i"] for e in snap] == list(range(12, 20))
        assert [e["seq"] for e in snap] == sorted(e["seq"] for e in snap)
        assert r.seq == 20

    def test_snapshot_filters(self):
        r = flight.FlightRecorder(capacity=32)
        r.record(flight.RPC, "info", "a")
        cut = r.seq
        r.record(flight.MEMBERSHIP, "warn", "b")
        r.record(flight.RPC, "error", "c")
        assert [e["msg"] for e in r.snapshot(category=flight.RPC)] == \
            ["a", "c"]
        assert [e["msg"] for e in r.snapshot(min_seq=cut)] == ["b", "c"]
        assert [e["msg"] for e in r.snapshot(count=1)] == ["c"]

    def test_disabled_recorder_drops_events(self):
        r = flight.FlightRecorder(capacity=8)
        r.set_enabled(False)
        r.record(flight.RPC, "info", "dropped")
        assert r.snapshot() == []
        r.set_enabled(True)
        r.record(flight.RPC, "info", "kept")
        assert [e["msg"] for e in r.snapshot()] == ["kept"]

    def test_event_carries_trace_id_from_open_span(self):
        from h2o3_tpu.util import telemetry

        r = flight.FlightRecorder(capacity=8)
        with telemetry.Span("health_unit") as sp:
            r.record(flight.COALESCE, "info", "in-span")
        assert r.snapshot()[-1]["trace_id"] == sp.trace_id


# ---------------------------------------------------------------------------
# rule arithmetic — windows must not fire on HEALTHY slow work


class TestRules:
    def test_rpc_stuck_no_false_stall_inside_budget(self):
        # a slow-but-sane call: aged half its ladder budget — ok
        entries = [{"method": "dtask", "target": "n1:1",
                    "age_s": 1.0, "budget_s": 2.0, "attempt": 1}]
        assert health.rpc_stuck_rule(entries, factor=3.0)[0] == "ok"

    def test_rpc_stuck_degrades_then_criticals(self):
        e = {"method": "dtask", "target": "n1:1",
             "age_s": 6.5, "budget_s": 2.0, "attempt": 2}
        assert health.rpc_stuck_rule([e], factor=3.0)[0] == "degraded"
        e2 = dict(e, age_s=13.0)
        state, detail = health.rpc_stuck_rule([e2], factor=3.0)
        assert state == "critical" and "dtask" in detail

    def test_fanout_done_is_never_a_stall(self):
        # all ranges settled: idle time is irrelevant
        entries = [{"kind": "map_reduce", "total": 4, "done": 4,
                    "idle_s": 99.0, "age_s": 100.0}]
        assert health.fanout_stall_rule(entries, window_s=5.0)[0] == "ok"

    def test_fanout_stall_windows(self):
        live = {"kind": "parse", "total": 8, "done": 3,
                "idle_s": 2.0, "age_s": 30.0}
        assert health.fanout_stall_rule([live], window_s=5.0)[0] == "ok"
        stalled = dict(live, idle_s=6.0)
        assert health.fanout_stall_rule(
            [stalled], window_s=5.0)[0] == "degraded"
        dead = dict(live, idle_s=11.0)
        assert health.fanout_stall_rule(
            [dead], window_s=5.0)[0] == "critical"

    def test_heartbeat_rule(self):
        # no cloud -> nothing to judge
        assert health.heartbeat_rule(None, 0.1, 4.0)[0] == "ok"
        # limit is factor*interval + 1s of absolute slack: a cycle 2
        # intervals late on a 100ms beat is still fine
        assert health.heartbeat_rule(0.2, 0.1, 4.0)[0] == "ok"
        assert health.heartbeat_rule(2.0, 0.1, 4.0)[0] == "degraded"
        assert health.heartbeat_rule(4.0, 0.1, 4.0)[0] == "critical"

    def test_http_saturation_rule(self):
        ok = health.http_saturation_rule(10, 512, 0, pct=80, shed_min=1)
        assert ok[0] == "ok"
        deep = health.http_saturation_rule(500, 512, 0, pct=80, shed_min=1)
        assert deep[0] == "degraded"
        full = health.http_saturation_rule(512, 512, 0, pct=80, shed_min=1)
        assert full[0] == "critical"
        shed = health.http_saturation_rule(0, 512, 3, pct=80, shed_min=1)
        assert shed[0] == "degraded"

    def test_compile_storm_rule(self):
        assert health.compile_storm_rule(5, 20)[0] == "ok"
        assert health.compile_storm_rule(25, 20)[0] == "degraded"
        assert health.compile_storm_rule(50, 20)[0] == "critical"

    def test_monitor_tick_is_all_ok_on_an_idle_process(self):
        mon = health.HealthMonitor(node="unit-idle", interval_s=0.05)
        mon.tick()
        states = {k: v["state"] for k, v in mon.verdicts().items()}
        assert set(states) == {"rpc_stuck", "fanout_stalled",
                               "heartbeat_overrun", "http_saturation",
                               "compile_storm"}
        assert all(s == "ok" for s in states.values())

    def test_monitor_transition_records_flight_event_and_gauge(self):
        mon = health.HealthMonitor(node="unit-trans", interval_s=0.05)
        seq0 = flight.RECORDER.seq
        fo = flight.FANOUTS.begin("unit_stall", 4)
        try:
            mon.stall_s = 0.01  # any idle time is a stall
            time.sleep(0.05)
            mon.tick()
            v = mon.verdicts()["fanout_stalled"]
            assert v["state"] in ("degraded", "critical")
            evs = [e for e in flight.RECORDER.snapshot(min_seq=seq0)
                   if e["category"] == flight.HEALTH
                   and e.get("check") == "fanout_stalled"]
            assert evs and evs[-1]["state"] == v["state"]
            g = health._HEALTH_STATE.value(
                node="unit-trans", check="fanout_stalled")
            assert g >= 1.0
        finally:
            fo.end()


# ---------------------------------------------------------------------------
# SIGUSR2 -> all-thread stacks into the ring


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                    reason="platform has no SIGUSR2")
class TestSigusr2:
    def test_signal_dumps_stacks_into_ring(self):
        assert flight.install_crash_hooks() in (True, False)
        seq0 = flight.RECORDER.seq
        os.kill(os.getpid(), signal.SIGUSR2)
        _wait_for(
            lambda: any(e["category"] == flight.STACKS
                        for e in flight.RECORDER.snapshot(min_seq=seq0)),
            msg="SIGUSR2 stack dump in the flight ring")
        evs = [e for e in flight.RECORDER.snapshot(min_seq=seq0)
               if e["category"] == flight.STACKS]
        # one event per thread, each naming the thread and carrying frames
        assert any("MainThread" in str(e.get("thread")) for e in evs)
        assert all(e.get("frames") for e in evs)


# ---------------------------------------------------------------------------
# federated /3/Diagnostics


@pytest.fixture()
def diag_cloud_server():
    from h2o3_tpu.api import start_server

    a = Cloud("healthcloud", "node-a", hb_interval=0.05)
    b = Cloud("healthcloud", "node-b", hb_interval=0.05)
    srv = None
    try:
        a.start([])
        b.start([a.info.addr])
        _wait_for(lambda: a.size() == 2 and b.size() == 2,
                  msg="2-node cloud formation")
        set_local_cloud(a)
        srv = start_server(port=0)
        yield a, b, srv
    finally:
        if srv is not None:
            srv.stop()
        set_local_cloud(None)
        a.stop()
        b.stop()


def _get(srv, path):
    try:
        with urllib.request.urlopen(srv.url + path) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestDiagnostics:
    def test_local_bundle_shape(self, diag_cloud_server):
        _a, _b, srv = diag_cloud_server
        st, out = _get(srv, "/3/Diagnostics")
        assert st == 200 and out["kind"] == "diagnostics"
        assert {"node", "pid", "knobs", "health", "flight", "slowops",
                "members", "threads"} <= set(out)
        assert isinstance(out["flight"], list)
        assert {m["name"] for m in out["members"]} == {"node-a", "node-b"}
        assert out["health"]["summary"]["state"] in health.STATES + (
            "unknown",)
        # the local route is renderable by the viewer too
        assert any(t.get("frames") for t in out["threads"])

    def test_cluster_bundle_all_up(self, diag_cloud_server):
        _a, _b, srv = diag_cloud_server
        st, out = _get(srv, "/3/Diagnostics?cluster=true&events=10")
        assert st == 200 and out["kind"] == "diagnostics_cluster"
        assert out["partial"] is False and out["errors"] == {}
        assert set(out["nodes"]) == {"node-a", "node-b"}
        for bundle in out["nodes"].values():
            assert bundle["kind"] == "diagnostics"
            assert len(bundle["flight"]) <= 10

    def test_cluster_bundle_partial_when_member_down(
            self, diag_cloud_server):
        a, b, srv = diag_cloud_server
        b.stop()
        a.client.pool.close_all()  # in-process stop leaves pooled sockets
        st, out = _get(srv, "/3/Diagnostics?cluster=true")
        assert st == 200  # degraded, NEVER a 5xx
        assert out["partial"] is True
        assert "node-b" in out["errors"]
        assert "node-a" in out["nodes"] and "node-b" not in out["nodes"]

    def test_slowops_carries_health_block(self, diag_cloud_server):
        _a, _b, srv = diag_cloud_server
        st, out = _get(srv, "/3/SlowOps")
        assert st == 200
        assert "health" in out and "checks" in out["health"]

    def test_profiler_cluster_carries_health_per_node(
            self, diag_cloud_server):
        _a, _b, srv = diag_cloud_server
        st, out = _get(srv, "/3/Profiler?cluster=true&duration=0.05")
        assert st == 200
        named = {n["node_name"]: n for n in out["nodes"]}
        assert {"node-a", "node-b"} <= set(named)
        # the health block rode the profiler_snapshot payload — one
        # scrape, no second RPC
        for node in ("node-a", "node-b"):
            assert "checks" in (named[node]["health"] or {})


# ---------------------------------------------------------------------------
# crash file -> scripts/diag_view.py round trip


class TestCrashRoundTrip:
    def test_persist_and_render(self, tmp_path):
        flight.record(flight.RPC, "error", "timeout",
                      method="dtask", target="gone:1", attempts=4)
        path = str(tmp_path / "flight-crash.json")
        assert flight.persist_crash(path, reason="unit") == path
        with open(path) as f:
            saved = json.load(f)
        assert saved["kind"] == "flight_crash"
        assert saved["reason"] == "unit"
        assert any(e.get("msg") == "timeout" for e in saved["events"])
        out = subprocess.run(
            [sys.executable, _DIAG_VIEW, path],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "flight crash file" in out.stdout
        assert "rpc/timeout" in out.stdout

    def test_viewer_renders_diagnostics_bundle(self, tmp_path):
        bundle = health.diagnostics_snapshot(events=20)
        path = str(tmp_path / "diag.json")
        with open(path, "w") as f:
            json.dump(bundle, f)
        out = subprocess.run(
            [sys.executable, _DIAG_VIEW, path, "--stacks"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert f"node {bundle['node']}" in out.stdout
        assert "health:" in out.stdout

    def test_viewer_rejects_garbage(self, tmp_path):
        path = str(tmp_path / "junk.json")
        with open(path, "w") as f:
            json.dump({"kind": "nonsense"}, f)
        out = subprocess.run(
            [sys.executable, _DIAG_VIEW, path],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 1
        assert "unrecognized" in out.stderr

    def test_crash_path_gated_on_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv("H2O3_TPU_FLIGHT_CRASH_DIR", raising=False)
        assert flight.crash_path() is None  # no dir -> no crash litter
        monkeypatch.setenv("H2O3_TPU_FLIGHT_CRASH_DIR", str(tmp_path))
        p = flight.crash_path(node="unit/node")
        assert p is not None and p.startswith(str(tmp_path))
        assert "/" not in os.path.basename(p).replace(".json", "")
        written = flight.persist_crash(reason="atexit-unit")
        assert written is not None and os.path.exists(written)
