"""Tree booster correctness — the M4 flagship kernel.

Reference analogue: hex/tree/gbm/GBMTest.java, DRFTest (SURVEY.md §4).
Oracles: sklearn GBM/HistGradientBoosting on identical data."""

import numpy as np
import pytest
from sklearn.ensemble import HistGradientBoostingClassifier, HistGradientBoostingRegressor

from h2o3_tpu import Frame
from h2o3_tpu.models.tree import DRF, GBM, XGBoost
from h2o3_tpu.ops.histogram import apply_bins, build_histogram_sharded, make_bins

import jax.numpy as jnp


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture(autouse=True)
def _clear_block_cache():
    """The block-fn lru cache key excludes the hist-impl env var; tests
    here flip it via monkeypatch, so the cache must be flushed after the
    env is restored or later same-key trains reuse the wrong impl."""
    yield
    from h2o3_tpu.models.tree.booster import _make_block_fn

    _make_block_fn.cache_clear()


def _classif_frame(rng, n=4000, informative=True):
    X = rng.normal(size=(n, 6)).astype(np.float64)
    logit = 1.5 * X[:, 0] - 2.0 * X[:, 1] + X[:, 2] * X[:, 3]
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    d = {f"x{i}": X[:, i] for i in range(6)}
    d["y"] = np.where(y > 0, "yes", "no")
    return Frame.from_dict(d), X, y


def test_histogram_matches_numpy(mesh, rng):
    n, F, K, B = 1003, 4, 3, 8
    bins = rng.integers(0, B + 1, size=(n, F)).astype(np.int32)
    nodes = rng.integers(-1, K, size=n).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32)
    pad = (-n) % 8
    bp = np.pad(bins, ((0, pad), (0, 0)))
    npad = np.pad(nodes, (0, pad), constant_values=-1)
    gp, hp = np.pad(g, (0, pad)), np.pad(h, (0, pad))
    hist = np.asarray(
        build_histogram_sharded(
            jnp.asarray(bp), jnp.asarray(npad), jnp.asarray(gp), jnp.asarray(hp),
            n_nodes=K, n_bins1=B + 1, mesh=mesh,
        )
    )
    want = np.zeros((K, F, B + 1, 3))
    for i in range(n):
        if nodes[i] < 0:
            continue
        for f in range(F):
            want[nodes[i], f, bins[i, f], 0] += g[i]
            want[nodes[i], f, bins[i, f], 1] += h[i]
            want[nodes[i], f, bins[i, f], 2] += 1
    np.testing.assert_allclose(hist, want, rtol=1e-4, atol=1e-4)


def test_binning_roundtrip(rng):
    X = rng.normal(size=(5000, 3))
    X[::17, 1] = np.nan
    edges = make_bins(X, nbins=32)
    bins = apply_bins(X, edges)
    assert bins.min() >= 0 and bins.max() <= 32
    assert np.all(bins[::17, 1] == 32)  # NA bucket
    # bins are monotone in the raw value
    order = np.argsort(X[:, 0])
    assert np.all(np.diff(bins[order, 0]) >= 0)


def test_gbm_binomial_learns(mesh, rng):
    fr, X, y = _classif_frame(rng)
    m = GBM(response_column="y", ntrees=30, max_depth=4, seed=1).train(fr)
    assert m.training_metrics.auc > 0.87, f"AUC {m.training_metrics.auc}"
    sk = HistGradientBoostingClassifier(max_iter=30, max_depth=4, early_stopping=False).fit(X, y)
    from sklearn.metrics import roc_auc_score

    sk_auc = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    assert m.training_metrics.auc > sk_auc - 0.03, f"{m.training_metrics.auc} vs sklearn {sk_auc}"


def test_gbm_regression_matches_sklearn_ballpark(mesh, rng):
    n = 3000
    X = rng.normal(size=(n, 5))
    y = 3 * X[:, 0] + np.sin(3 * X[:, 1]) * 2 + X[:, 2] * X[:, 3] + 0.1 * rng.normal(size=n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(5)} | {"y": y})
    m = GBM(response_column="y", ntrees=50, max_depth=4, seed=1).train(fr)
    sk = HistGradientBoostingRegressor(max_iter=50, max_depth=4, early_stopping=False).fit(X, y)
    from sklearn.metrics import mean_squared_error

    sk_mse = mean_squared_error(y, sk.predict(X))
    assert m.training_metrics.mse < max(2.5 * sk_mse, 0.15), (
        f"{m.training_metrics.mse} vs sklearn {sk_mse}"
    )


def test_gbm_multinomial(mesh, rng):
    n = 3000
    X = rng.normal(size=(n, 4))
    score = np.stack([X[:, 0], X[:, 1], -X[:, 0] - X[:, 1]], axis=1) + 0.3 * rng.normal(size=(n, 3))
    y = score.argmax(axis=1)
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(4)} | {"y": np.array(["a", "b", "c"])[y]}
    )
    m = GBM(response_column="y", ntrees=20, max_depth=4, seed=1).train(fr)
    assert m.training_metrics.hit_ratios[0] > 0.85
    pred = m.predict(fr)
    assert pred.names[0] == "predict"
    assert set(pred.col("predict").domain) == {"a", "b", "c"}


def test_gbm_handles_nas_and_categoricals(mesh, rng):
    n = 2000
    x = rng.normal(size=n)
    x[::5] = np.nan
    g = rng.integers(0, 3, n)
    y = np.where(np.isnan(x), 2.0, x) + np.array([0.0, 2.0, -1.0])[g] + 0.1 * rng.normal(size=n)
    fr = Frame.from_dict({"x": x, "g": np.array(["u", "v", "w"])[g], "y": y})
    m = GBM(response_column="y", ntrees=30, max_depth=4, min_rows=5, seed=1).train(fr)
    assert m.training_metrics.r2 > 0.8


def test_gbm_early_stopping(mesh, rng):
    fr, X, y = _classif_frame(rng, n=1500)
    m = GBM(
        response_column="y", ntrees=200, max_depth=3, stopping_rounds=3,
        stopping_tolerance=0.01, seed=1,
    ).train(fr)
    assert m.ntrees_built < 200, "early stopping should have triggered"


def test_drf_classification(mesh, rng):
    fr, X, y = _classif_frame(rng)
    m = DRF(response_column="y", ntrees=30, max_depth=8, seed=1).train(fr)
    assert m.training_metrics.auc > 0.9
    probs = m._predict_raw(fr)
    assert probs.shape == (fr.nrows, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_drf_regression(mesh, rng):
    n = 2000
    X = rng.normal(size=(n, 5))
    y = 2 * X[:, 0] - X[:, 1] + 0.2 * rng.normal(size=n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(5)} | {"y": y})
    m = DRF(response_column="y", ntrees=40, max_depth=10, seed=1).train(fr)
    assert m.training_metrics.r2 > 0.7


def test_xgboost_binomial(mesh, rng):
    fr, X, y = _classif_frame(rng)
    m = XGBoost(response_column="y", ntrees=30, max_depth=5, learn_rate=0.3, seed=1).train(fr)
    assert m.training_metrics.auc > 0.95
    assert m.params.tree_method == "tpu_hist"


def test_xgboost_regularization_shrinks_leaves(mesh, rng):
    fr, X, y = _classif_frame(rng, n=1500)
    m1 = XGBoost(response_column="y", ntrees=5, max_depth=4, reg_lambda=0.0, seed=1).train(fr)
    m2 = XGBoost(response_column="y", ntrees=5, max_depth=4, reg_lambda=100.0, seed=1).train(fr)
    l1 = np.abs(np.concatenate([t for t in m1.booster.trees_per_class[0].leaf])).max()
    l2 = np.abs(np.concatenate([t for t in m2.booster.trees_per_class[0].leaf])).max()
    assert l2 < l1


def test_variable_importance(mesh, rng):
    n = 2000
    X = rng.normal(size=(n, 4))
    y = 5 * X[:, 2] + 0.1 * rng.normal(size=n)  # only x2 matters
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(4)} | {"y": y})
    m = GBM(response_column="y", ntrees=10, max_depth=3, seed=1).train(fr)
    vi = m.variable_importances()
    assert vi["x2"] == max(vi.values())


# ---------------------------------------------------------------------------
# histogram-subtraction level flow (H2O3_TPU_TREE_SUBTRACT)


def _train_margins(X, y, objective, monkeypatch, subtract, impl=None,
                   params=None, **kw):
    from h2o3_tpu.models.tree.booster import (
        TreeParams, _make_block_fn, train_boosted)
    from h2o3_tpu.models.tree.common import init_margin

    monkeypatch.setenv("H2O3_TPU_TREE_SUBTRACT", "1" if subtract else "0")
    if impl is not None:
        monkeypatch.setenv("H2O3_TPU_HIST_IMPL", impl)
    _make_block_fn.cache_clear()
    params = params or TreeParams(ntrees=8, max_depth=4, nbins=32, seed=3)
    f0 = init_margin(objective, y, 1)
    model = train_boosted(X, objective, y, 1, f0, params, **kw)
    return model.predict_margin(X)


class TestHistogramSubtraction:
    """Subtract mode builds only the smaller sibling per split and derives
    the larger by subtraction; terminal leaves come from the last split's
    child stats. Same rows, same sums — predictions must match the direct
    per-level build to f32 tolerance."""

    def test_binomial_equivalence(self, mesh, rng, monkeypatch):
        n = 2000
        X = rng.normal(size=(n, 6)).astype(np.float32)
        logit = X[:, 0] + X[:, 1] * X[:, 2]
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
        a = _train_margins(X, y, "bernoulli", monkeypatch, subtract=False)
        b = _train_margins(X, y, "bernoulli", monkeypatch, subtract=True)
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_weighted_nas_equivalence(self, mesh, rng, monkeypatch):
        n = 1500
        X = rng.normal(size=(n, 5)).astype(np.float32)
        X[rng.random((n, 5)) < 0.15] = np.nan  # exercise the NA bucket
        y = np.where(np.isnan(X[:, 0]), 0.5, X[:, 0]) * 2 + rng.normal(size=n)
        w = rng.integers(1, 4, size=n).astype(np.float64)
        a = _train_margins(X, y, "gaussian", monkeypatch, subtract=False,
                           weights=w)
        b = _train_margins(X, y, "gaussian", monkeypatch, subtract=True,
                           weights=w)
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_monotone_equivalence(self, mesh, rng, monkeypatch):
        n = 1500
        X = rng.normal(size=(n, 4)).astype(np.float32)
        y = 2 * X[:, 0] + 0.2 * rng.normal(size=n)
        mono = np.array([1, 0, 0, 0], dtype=np.int32)
        a = _train_margins(X, y, "gaussian", monkeypatch, subtract=False,
                           monotone=mono)
        b = _train_margins(X, y, "gaussian", monkeypatch, subtract=True,
                           monotone=mono)
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_pallas_subtract_tree_matches_scatter(mesh, rng, monkeypatch):
    """The TPU-default combination (pallas kernels + histogram
    subtraction) must grow the same trees as the scatter oracle. Run in
    Pallas interpreter mode on a small config — this is the program the
    real-TPU bench compiles."""
    from h2o3_tpu.models.tree.booster import TreeParams

    n = 2048
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] +
         0.2 * rng.normal(size=n) > 0).astype(np.float64)
    params = TreeParams(ntrees=2, max_depth=3, nbins=16, seed=5)

    a = _train_margins(X, y, "bernoulli", monkeypatch, subtract=False,
                       impl="scatter", params=params)
    b = _train_margins(X, y, "bernoulli", monkeypatch, subtract=True,
                       impl="pallas", params=params)
    np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-4)
