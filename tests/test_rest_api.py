"""REST v3 API over real HTTP sockets (reference tests run real sockets on
localhost too — SURVEY.md §4 'no mocked network backends')."""

import json
import urllib.request
import urllib.parse

import numpy as np
import pytest

from h2o3_tpu.api import start_server


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys

CSV = "sepal_len,species,weight\n5.1,setosa,1.0\n4.9,setosa,0.9\n6.3,virginica,1.4\n5.8,virginica,1.2\n6.1,virginica,1.3\n5.0,setosa,1.05\n"


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _req(server, method, path, data=None, raw=False):
    url = server.url + path
    body = None
    headers = {}
    if data is not None:
        body = json.dumps(data).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=body, headers=headers, method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _upload_and_parse(server, csv, dest):
    st, up = _req(server, "POST", "/3/PostFile", {"data": csv})
    assert st == 200
    st, out = _req(
        server, "POST", "/3/Parse",
        {"source_frames": [up["destination_frame"]], "destination_frame": dest},
    )
    assert st == 200, out
    return out["destination_frame"]["name"]


class TestCloudAndMetadata:
    def test_cloud(self, server):
        st, out = _req(server, "GET", "/3/Cloud")
        assert st == 200
        assert out["cloud_size"] == 1
        assert out["cloud_healthy"] is True

    def test_endpoints_inventory(self, server):
        st, out = _req(server, "GET", "/3/Metadata/endpoints")
        assert st == 200
        assert len(out["routes"]) > 25

    def test_capabilities_lists_all_algos(self, server):
        st, out = _req(server, "GET", "/3/Capabilities")
        names = {c["name"] for c in out["capabilities"]}
        assert {"gbm", "glm", "deeplearning", "kmeans", "xgboost", "coxph"} <= names

    def test_404_error_schema(self, server):
        st, out = _req(server, "GET", "/3/Nope")
        assert st == 404
        assert "msg" in out and out["http_status"] == 404


class TestFramesOverRest:
    def test_upload_parse_get_delete(self, server):
        key = _upload_and_parse(server, CSV, "iris_mini.hex")
        assert key == "iris_mini.hex"
        st, out = _req(server, "GET", "/3/Frames/iris_mini.hex")
        assert st == 200
        fr = out["frames"][0]
        assert fr["rows"] == 6
        assert fr["column_names"] == ["sepal_len", "species", "weight"]
        cols = {c["label"]: c for c in fr["columns"]}
        assert cols["species"]["type"] == "cat"
        assert set(cols["species"]["domain"]) == {"setosa", "virginica"}
        assert cols["sepal_len"]["mean"] == pytest.approx(5.533, abs=1e-2)

        st, _ = _req(server, "DELETE", "/3/Frames/iris_mini.hex")
        assert st == 200
        st, _ = _req(server, "GET", "/3/Frames/iris_mini.hex")
        assert st == 404

    def test_parse_setup_guess(self, server):
        st, up = _req(server, "POST", "/3/PostFile", {"data": CSV})
        st, out = _req(
            server, "POST", "/3/ParseSetup",
            {"source_frames": [up["destination_frame"]]},
        )
        assert st == 200
        assert out["column_names"] == ["sepal_len", "species", "weight"]
        assert out["number_columns"] == 3

    def test_download_roundtrip(self, server):
        key = _upload_and_parse(server, CSV, "dl_rt.hex")
        st, raw = _req(server, "GET", f"/3/DownloadDataset?frame_id={key}", raw=True)
        assert st == 200
        assert raw.decode().splitlines()[0] == "sepal_len,species,weight"

    def test_split_frame(self, server):
        csv = "x\n" + "\n".join(str(i) for i in range(200))
        key = _upload_and_parse(server, csv, "sf.hex")
        st, out = _req(
            server, "POST", "/3/SplitFrame",
            {"dataset": key, "ratios": [0.7], "seed": 42},
        )
        assert st == 200
        keys = [d["name"] for d in out["destination_frames"]]
        assert len(keys) == 2
        sizes = []
        for k in keys:
            st, fo = _req(server, "GET", f"/3/Frames/{k}")
            sizes.append(fo["frames"][0]["rows"])
        assert sum(sizes) == 200
        assert 110 <= sizes[0] <= 170


class TestRapidsOverRest:
    def test_session_and_exec(self, server):
        st, s = _req(server, "POST", "/4/sessions")
        assert st == 200
        sid = s["session_key"]
        key = _upload_and_parse(server, CSV, "rap.hex")
        st, out = _req(
            server, "POST", "/99/Rapids",
            {"ast": f"(mean (cols {key} 'sepal_len') 0 0)", "session_id": sid},
        )
        assert st == 200, out
        val = out.get("scalar")
        if isinstance(val, list):
            val = val[0]
        assert val == pytest.approx(5.533, abs=1e-2)
        st, out = _req(server, "DELETE", f"/4/sessions/{sid}")
        assert st == 200

    def test_rapids_error_is_400(self, server):
        st, out = _req(server, "POST", "/99/Rapids", {"ast": "(not_a_prim 1)"})
        assert st == 400


class TestModelsOverRest:
    def _train_frame(self, server, rng, dest):
        n = 300
        x0 = rng.normal(size=n)
        x1 = rng.normal(size=n)
        y = np.where(x0 + 0.5 * x1 + rng.normal(size=n) * 0.4 > 0, "yes", "no")
        rows = "\n".join(f"{a:.5f},{b:.5f},{c}" for a, b, c in zip(x0, x1, y))
        return _upload_and_parse(server, "x0,x1,y\n" + rows + "\n", dest)

    def test_train_get_predict_delete(self, server):
        rng = np.random.default_rng(3)
        key = self._train_frame(server, rng, "trainfr.hex")
        st, out = _req(
            server, "POST", "/3/ModelBuilders/gbm",
            {"training_frame": key, "response_column": "y", "ntrees": 5,
             "max_depth": "3", "seed": 1, "model_id": "gbm_rest_1"},
        )
        assert st == 200, out
        assert out["model_id"]["name"] == "gbm_rest_1"
        assert out["job"]["status"] == "DONE"

        st, out = _req(server, "GET", "/3/Models/gbm_rest_1")
        assert st == 200
        mo = out["models"][0]
        assert mo["algo"] == "gbm"
        assert mo["output"]["model_category"] == "Binomial"
        assert mo["output"]["training_metrics"]["auc"] > 0.8
        assert mo["parameters"]["ntrees"] == 5

        st, out = _req(
            server, "POST", f"/3/Predictions/models/gbm_rest_1/frames/{key}"
        )
        assert st == 200
        pred_key = out["model_metrics"][0]["predictions_frame"]["name"]
        st, out = _req(server, "GET", f"/3/Frames/{pred_key}")
        assert out["frames"][0]["rows"] == 300
        assert "predict" in out["frames"][0]["column_names"]

        st, raw = _req(server, "GET", "/3/Models/gbm_rest_1/mojo", raw=True)
        assert st == 200 and raw[:2] == b"PK"  # a zip

        st, _ = _req(server, "DELETE", "/3/Models/gbm_rest_1")
        assert st == 200
        st, _ = _req(server, "GET", "/3/Models/gbm_rest_1")
        assert st == 404

    def test_train_bad_params_is_400(self, server):
        rng = np.random.default_rng(4)
        key = self._train_frame(server, rng, "badp.hex")
        st, out = _req(
            server, "POST", "/3/ModelBuilders/glm",
            {"training_frame": key, "response_column": "y", "family": "nope"},
        )
        assert st == 400
        assert "family" in out["msg"]

    def test_unknown_algo_404(self, server):
        st, _ = _req(server, "POST", "/3/ModelBuilders/nosuch", {})
        assert st == 404

    def test_grid_over_rest(self, server):
        rng = np.random.default_rng(5)
        key = self._train_frame(server, rng, "gridfr.hex")
        st, out = _req(
            server, "POST", "/99/Grid/glm",
            {
                "training_frame": key,
                "response_column": "y",
                "family": "binomial",
                "hyper_parameters": {"lambda_": [0.0, 0.1]},
            },
        )
        assert st == 200, out
        gid = out["grid_id"]["name"]
        assert len(out["model_ids"]) == 2
        st, out = _req(server, "GET", f"/99/Grids/{gid}")
        assert st == 200
        assert len(out["model_ids"]) == 2


class TestJobsOverRest:
    def test_jobs_listed(self, server):
        st, out = _req(server, "GET", "/3/Jobs")
        assert st == 200
        assert isinstance(out["jobs"], list)


class TestRestReviewFixes:
    def test_split_exact_ratios_no_empty_extra(self, server):
        csv = "x\n" + "\n".join(str(i) for i in range(100))
        key = _upload_and_parse(server, csv, "sf2.hex")
        st, out = _req(
            server, "POST", "/3/SplitFrame",
            {"dataset": key, "ratios": [0.5, 0.5], "seed": 1,
             "destination_frames": ["sfa.hex", "sfb.hex"]},
        )
        assert st == 200
        keys = [d["name"] for d in out["destination_frames"]]
        assert keys == ["sfa.hex", "sfb.hex"]

    def test_parse_honors_forced_column_types(self, server):
        csv = "id,v\n1,10\n2,20\n1,30\n"
        st, up = _req(server, "POST", "/3/PostFile", {"data": csv})
        st, out = _req(
            server, "POST", "/3/Parse",
            {"source_frames": [up["destination_frame"]],
             "destination_frame": "typed.hex",
             "column_names": ["id", "v"],
             "column_types": ["enum", "numeric"]},
        )
        assert st == 200, out
        st, out = _req(server, "GET", "/3/Frames/typed.hex")
        cols = {c["label"]: c["type"] for c in out["frames"][0]["columns"]}
        assert cols["id"] == "cat"
        assert cols["v"] == "num"

    def test_no_phantom_created_jobs_after_train(self, server):
        rng = np.random.default_rng(9)
        key = TestModelsOverRest()._train_frame(server, rng, "jobfr.hex")
        st, _ = _req(
            server, "POST", "/3/ModelBuilders/glm",
            {"training_frame": key, "response_column": "y", "family": "binomial"},
        )
        assert st == 200
        st, out = _req(server, "GET", "/3/Jobs")
        assert all(j["status"] != "CREATED" for j in out["jobs"])
