"""No accepted-but-ignored common params — the set-and-compare harness.

VERDICT r2 item 2: GLM advertised families that crashed and accepted
lambda_search/solver values it ignored; DL ignored ``checkpoint``. The fix is
structural: ``ModelBuilder._validate`` rejects any guarded common param a
builder doesn't declare in ``SUPPORTED_COMMON`` (reference analogue: parameter
validation in hex/ModelBuilder.init rejects unsupported combos loudly).

This test sweeps EVERY registered algo x EVERY guarded param: either the
builder declares it (and validation accepts it) or validation raises.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api.registry import algo_map

GUARDED = {
    "weights_column": "w",
    "offset_column": "off",
    "checkpoint": "some-model-key",
    "stopping_rounds": 3,
    "max_runtime_secs": 5.0,
    "categorical_encoding": "one_hot_explicit",
}


@pytest.fixture(scope="module")
def tiny_frame():
    rng = np.random.default_rng(7)
    n = 40
    return Frame.from_dict(
        {
            "x0": rng.normal(size=n),
            "x1": rng.normal(size=n),
            "w": np.ones(n),
            "off": np.zeros(n),
            "y": np.where(rng.random(n) > 0.5, "a", "b"),
        }
    )


ALGOS = sorted(algo_map())


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("field", sorted(GUARDED))
def test_guarded_param_never_silently_ignored(algo, field, tiny_frame):
    builder_cls, params_cls = algo_map()[algo]
    from dataclasses import fields as dc_fields

    names = {f.name for f in dc_fields(params_cls)}
    if field not in names:
        pytest.skip(f"{algo} params have no {field} field")
    kwargs = {field: GUARDED[field]}
    if "response_column" in names:
        kwargs["response_column"] = "y"
    params = params_cls(**kwargs)
    builder = builder_cls(params)

    if field in builder_cls.SUPPORTED_COMMON:
        # declared supported: the guard must NOT reject it (other validation
        # errors are fine — e.g. checkpoint key resolution happens at fit)
        try:
            builder._validate(tiny_frame)
        except ValueError as e:
            assert "does not support" not in str(e), (
                f"{algo} declares {field} in SUPPORTED_COMMON but the guard "
                f"rejected it: {e}"
            )
    else:
        with pytest.raises(ValueError, match="does not support"):
            builder._validate(tiny_frame)


def test_supported_common_is_subset_of_guarded():
    for algo, (builder_cls, _) in algo_map().items():
        from h2o3_tpu.models.framework import ModelBuilder

        unknown = builder_cls.SUPPORTED_COMMON - set(ModelBuilder._GUARDED_DEFAULTS)
        assert not unknown, f"{algo} declares unguarded params {unknown}"
