"""REST binary persistence + Generic (MOJO import) — VERDICT r2 item 4.

Reference: Model.exportBinaryModel / importBinaryModel behind
``/3/Models/{id}/save`` + ``/99/Models.bin``, FramePersist save/load, and
``hex/generic/`` (MOJO -> first-class servable model). All over real HTTP.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import start_server


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys

CSV = "x0,x1,y\n" + "\n".join(
    f"{a:.3f},{b:.3f},{'yes' if a + b > 0 else 'no'}"
    for a, b in np.random.default_rng(5).normal(size=(300, 2))
)


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _req(server, method, path, data=None):
    url = server.url + path
    body = None
    headers = {}
    if data is not None:
        body = json.dumps(data).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=body, headers=headers, method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _upload_and_parse(server, csv, dest):
    st, up = _req(server, "POST", "/3/PostFile", {"data": csv})
    assert st == 200
    st, out = _req(
        server, "POST", "/3/Parse",
        {"source_frames": [up["destination_frame"]], "destination_frame": dest},
    )
    assert st == 200, out
    return out["destination_frame"]["name"]


def _train_gbm(server, frame_id, model_id):
    st, out = _req(
        server, "POST", "/3/ModelBuilders/gbm",
        {"training_frame": frame_id, "response_column": "y", "ntrees": 5,
         "max_depth": 3, "seed": 42, "min_rows": 5, "model_id": model_id},
    )
    assert st == 200, out
    return out["model_id"]["name"]


def _predictions(server, model_id, frame_id):
    st, out = _req(
        server, "POST", f"/3/Predictions/models/{model_id}/frames/{frame_id}", {}
    )
    assert st == 200, out
    pred_id = out["model_metrics"][0]["predictions_frame"]["name"]
    st, fr = _req(server, "GET", f"/3/Frames/{pred_id}?row_count=300")
    assert st == 200
    return fr


class TestBinaryModelPersistOverRest:
    def test_save_restart_load_predict_parity(self, server, tmp_path):
        fid = _upload_and_parse(server, CSV, "persist_train")
        mid = _train_gbm(server, fid, "gbm_persist")
        before = _predictions(server, mid, fid)

        st, out = _req(server, "POST", f"/3/Models/{mid}/save",
                       {"dir": str(tmp_path) + os.sep})
        assert st == 200, out
        path = out["dir"]
        assert os.path.exists(path)

        # simulate restart: remove the model from the DKV entirely
        st, _ = _req(server, "DELETE", f"/3/Models/{mid}")
        assert st == 200
        st, out = _req(server, "GET", f"/3/Models/{mid}")
        assert st == 404

        st, out = _req(server, "POST", "/99/Models.bin", {"dir": path})
        assert st == 200, out
        assert out["models"][0]["model_id"]["name"] == mid

        after = _predictions(server, mid, fid)
        # exact value parity (the prediction frame key itself is random)
        b = {c["label"]: c["data"] for c in before["frames"][0]["columns"]}
        a = {c["label"]: c["data"] for c in after["frames"][0]["columns"]}
        assert b == a

    def test_save_missing_dir_is_400(self, server):
        fid = _upload_and_parse(server, CSV, "persist_train2")
        mid = _train_gbm(server, fid, "gbm_persist2")
        st, out = _req(server, "POST", f"/3/Models/{mid}/save", {})
        assert st == 400

    def test_load_missing_file_is_404(self, server):
        st, out = _req(server, "POST", "/99/Models.bin",
                       {"dir": "/nonexistent/m.bin"})
        assert st == 404


class TestFramePersistOverRest:
    def test_frame_save_load_roundtrip(self, server, tmp_path):
        fid = _upload_and_parse(server, CSV, "fp_frame")
        st, before = _req(server, "GET", f"/3/Frames/{fid}")
        assert st == 200

        st, out = _req(server, "POST", f"/3/Frames/{fid}/save",
                       {"dir": str(tmp_path) + os.sep})
        assert st == 200, out
        path = out["dir"]

        st, _ = _req(server, "DELETE", f"/3/Frames/{fid}")
        assert st == 200

        st, out = _req(server, "POST", "/3/Frames/load",
                       {"dir": path, "frame_id": fid})
        assert st == 200, out
        st, after = _req(server, "GET", f"/3/Frames/{fid}")
        assert st == 200
        assert before["frames"][0]["rows"] == after["frames"][0]["rows"]
        assert before["frames"][0]["columns"] == after["frames"][0]["columns"]


class TestGenericMojoImport:
    def test_mojo_roundtrip_over_http(self, server, tmp_path):
        """train -> download mojo -> import as Generic -> predict parity."""
        fid = _upload_and_parse(server, CSV, "mojo_train")
        mid = _train_gbm(server, fid, "gbm_mojo_src")
        before = _predictions(server, mid, fid)

        # download the mojo archive over HTTP
        url = server.url + f"/3/Models/{mid}/mojo"
        with urllib.request.urlopen(url) as resp:
            blob = resp.read()
        mojo_path = tmp_path / "m.mojo"
        mojo_path.write_bytes(blob)

        st, out = _req(server, "POST", "/99/Models.mojo",
                       {"dir": str(mojo_path), "model_id": "generic_1"})
        assert st == 200, out
        assert out["models"][0]["algo"] == "generic"
        assert out["models"][0]["source_algo"] == "gbm"

        after = _predictions(server, "generic_1", fid)
        # same probabilities (labels may use a default threshold)
        b = {c["label"]: c["data"] for c in before["frames"][0]["columns"]}
        a = {c["label"]: c["data"] for c in after["frames"][0]["columns"]}
        for col in ("pyes", "pno"):
            np.testing.assert_allclose(a[col], b[col], rtol=1e-5, atol=1e-6)

    def test_generic_via_modelbuilders_route(self, server, tmp_path):
        """hex/generic registers as an algo: POST /3/ModelBuilders/generic."""
        fid = _upload_and_parse(server, CSV, "mojo_train3")
        mid = _train_gbm(server, fid, "gbm_mojo_src3")
        url = server.url + f"/3/Models/{mid}/mojo"
        with urllib.request.urlopen(url) as resp:
            blob = resp.read()
        mojo_path = tmp_path / "m3.mojo"
        mojo_path.write_bytes(blob)

        st, out = _req(server, "POST", "/3/ModelBuilders/generic",
                       {"path": str(mojo_path)})
        assert st == 200, out
        gid = out["model_id"]["name"]
        st, out = _req(server, "GET", f"/3/Models/{gid}")
        assert st == 200

    def test_import_missing_mojo_is_404(self, server):
        st, out = _req(server, "POST", "/99/Models.mojo",
                       {"dir": "/nonexistent/m.mojo"})
        assert st == 404


class TestLoadDoesNotClobber:
    def test_load_with_new_id_keeps_live_model(self, server, tmp_path):
        """Restoring a snapshot under a NEW id must not destroy the live
        model still registered under the file's saved key."""
        fid = _upload_and_parse(server, CSV, "clobber_train")
        mid = _train_gbm(server, fid, "gbm_live")
        st, out = _req(server, "POST", f"/3/Models/{mid}/save",
                       {"dir": str(tmp_path)})
        assert st == 200
        path = out["dir"]

        st, out = _req(server, "POST", "/99/Models.bin",
                       {"dir": path, "model_id": "gbm_copy"})
        assert st == 200, out
        assert out["models"][0]["model_id"]["name"] == "gbm_copy"
        # the original stays live and scorable
        st, _ = _req(server, "GET", f"/3/Models/{mid}")
        assert st == 200
        _predictions(server, mid, fid)
        _predictions(server, "gbm_copy", fid)


class TestGridPersistOverRest:
    def test_grid_export_import_roundtrip(self, server, tmp_path):
        fid = _upload_and_parse(server, CSV, "grid_train")
        st, out = _req(server, "POST", "/99/Grid/gbm",
                       {"training_frame": fid, "response_column": "y",
                        "ntrees": 3, "seed": 1, "min_rows": 5,
                        "hyper_parameters": {"max_depth": [2, 3]}})
        assert st == 200, out
        gid = out["grid_id"]["name"]
        st, before = _req(server, "GET", f"/99/Grids/{gid}")
        assert st == 200

        st, out = _req(server, "POST", f"/99/Grids/{gid}/export",
                       {"dir": str(tmp_path)})
        assert st == 200, out
        path = out["dir"]

        st, out = _req(server, "POST", "/99/Grids/import", {"dir": path})
        assert st == 200, out
        assert out["grid_id"]["name"] == gid
        names = {
            m["name"] if isinstance(m, dict) else m for m in before["model_ids"]
        }
        assert set(out["model_ids"]) == names
        # member models are scorable again
        _predictions(server, out["model_ids"][0], fid)
