"""Binary model save/load + checkpoint-continue training.

Reference behaviors pinned: ``Model.exportBinaryModel``/``importBinaryModel``
round-trip (hex/Model.java), and checkpoint restart semantics
(``hex/tree/SharedTree.java:131-136``): training k trees then continuing to
2k must equal training 2k straight — the per-tree RNG keying makes this
exact, not approximate.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.persist import load_model, save_model


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _toy_frame(n=400, seed=0, classify=True):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cat = rng.integers(0, 3, n).astype(np.int32)
    logit = x1 + 0.5 * x2 + (cat == 1) * 0.8
    if classify:
        y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.int32)
        ycol = Column("y", y, ColType.CAT, ["no", "yes"])
    else:
        ycol = Column("y", logit + rng.normal(size=n) * 0.1, ColType.NUM)
    return Frame(
        [
            Column("x1", x1, ColType.NUM),
            Column("x2", x2, ColType.NUM),
            Column("c", cat, ColType.CAT, ["a", "b", "c"]),
            ycol,
        ]
    )


def _roundtrip(model, fr, tmp_path, name):
    p = tmp_path / f"{name}.bin"
    save_model(model, p)
    back = load_model(p)
    want = model._predict_raw(fr)
    got = back._predict_raw(fr)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    assert back.algo_name == model.algo_name
    assert back.data_info.predictor_names == model.data_info.predictor_names
    # metrics survive
    assert back.training_metrics is not None
    return back


def test_gbm_binary_roundtrip(tmp_path):
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _toy_frame()
    m = GBM(ntrees=5, max_depth=3, response_column="y", seed=1).train(fr)
    back = _roundtrip(m, fr, tmp_path, "gbm")
    assert back.booster.trees_per_class[0].ntrees == 5


def test_glm_roundtrip(tmp_path):
    from h2o3_tpu.models.glm import GLM

    fr = _toy_frame(classify=False)
    m = GLM(family="gaussian", response_column="y", seed=1).train(fr)
    _roundtrip(m, fr, tmp_path, "glm")


def test_kmeans_roundtrip(tmp_path):
    from h2o3_tpu.models.kmeans import KMeans

    fr = _toy_frame().drop("y")
    m = KMeans(k=3, response_column=None, seed=1).train(fr)
    p = tmp_path / "km.bin"
    save_model(m, p)
    back = load_model(p)
    np.testing.assert_allclose(back.centers, m.centers)


def test_deeplearning_roundtrip(tmp_path):
    from h2o3_tpu.models.deeplearning import DeepLearning

    fr = _toy_frame()
    m = DeepLearning(
        hidden=[8], epochs=2, response_column="y", seed=1
    ).train(fr)
    _roundtrip(m, fr, tmp_path, "dl")


def test_loaded_model_is_in_dkv(tmp_path):
    from h2o3_tpu.keyed import DKV
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _toy_frame()
    m = GBM(ntrees=3, max_depth=2, response_column="y", seed=1).train(fr)
    p = tmp_path / "m.bin"
    save_model(m, p)
    DKV.remove(m.key)
    back = load_model(p)
    assert DKV.get(back.key) is back


def test_no_pickle_in_container(tmp_path):
    import zipfile

    from h2o3_tpu.models.tree.gbm import GBM

    fr = _toy_frame()
    m = GBM(ntrees=2, max_depth=2, response_column="y", seed=1).train(fr)
    p = tmp_path / "m.bin"
    save_model(m, p)
    with zipfile.ZipFile(p) as z:
        names = z.namelist()
        assert set(names) == {"meta.json", "model.json", "arrays.npz"}
        # npz must not need pickle to load
        import io

        np.load(io.BytesIO(z.read("arrays.npz")), allow_pickle=False)


# ---------------------------------------------------------------------------
# checkpoint-continue


@pytest.mark.parametrize("algo", ["gbm", "drf", "xgboost"])
def test_checkpoint_continue_equals_straight_run(algo):
    from h2o3_tpu.models.tree.drf import DRF
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.models.tree.xgboost import XGBoost

    cls = {"gbm": GBM, "drf": DRF, "xgboost": XGBoost}[algo]
    fr = _toy_frame(seed=3)
    kw = dict(max_depth=3, response_column="y", seed=7, sample_rate=0.7)

    full = cls(ntrees=8, **kw).train(fr)
    half = cls(ntrees=4, **kw).train(fr)
    cont = cls(ntrees=8, checkpoint=half.key, **kw).train(fr)

    assert cont.booster.trees_per_class[0].ntrees == 8
    np.testing.assert_allclose(
        cont._predict_raw(fr), full._predict_raw(fr), rtol=1e-5, atol=1e-6
    )


def test_checkpoint_requires_more_trees():
    from h2o3_tpu.models.tree.gbm import GBM

    fr = _toy_frame(seed=4)
    half = GBM(ntrees=4, max_depth=2, response_column="y", seed=7).train(fr)
    with pytest.raises(ValueError, match="must exceed"):
        GBM(ntrees=4, max_depth=2, response_column="y", seed=7,
            checkpoint=half.key).train(fr)


class TestDeepLearningCheckpoint:
    """DL checkpoint-continue (CheckpointUtils covers DL too;
    SharedTree.java:131-136): k epochs then k more == straight 2k."""

    def test_k_plus_k_equals_2k(self, rng):
        from h2o3_tpu.models.deeplearning import DeepLearning

        n = 600
        X = rng.normal(size=(n, 4)).astype(np.float64)
        y = X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.normal(size=n)
        fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(4)} | {"y": y})
        kw = dict(response_column="y", hidden=[8], seed=11, mini_batch_size=64)

        straight = DeepLearning(epochs=6, **kw).train(fr)
        first = DeepLearning(epochs=3, **kw).train(fr)
        resumed = DeepLearning(epochs=6, checkpoint=first.key, **kw).train(fr)

        assert resumed.epochs_trained == straight.epochs_trained == 6
        for (W1, b1), (W2, b2) in zip(resumed.net_params, straight.net_params):
            np.testing.assert_allclose(W1, W2, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(b1, b2, rtol=1e-5, atol=1e-6)

    def test_checkpoint_validation(self, rng):
        from h2o3_tpu.models.deeplearning import DeepLearning

        n = 200
        X = rng.normal(size=(n, 3))
        y = X[:, 0] + 0.1 * rng.normal(size=n)
        fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
        m = DeepLearning(response_column="y", hidden=[8], epochs=2, seed=1).train(fr)
        with pytest.raises(ValueError, match="hidden"):
            DeepLearning(response_column="y", hidden=[16], epochs=4,
                         checkpoint=m.key, seed=1).train(fr)
        with pytest.raises(ValueError, match="must exceed"):
            DeepLearning(response_column="y", hidden=[8], epochs=2,
                         checkpoint=m.key, seed=1).train(fr)
