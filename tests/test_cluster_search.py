"""Distributed model search (h2o3_tpu/cluster/search.py) on in-process
clouds: grid fan-out must be BIT-IDENTICAL to the single-node walk at a
fixed seed regardless of member count or completion order, progress must
stream back into the caller's Job, and the per-cell seed contract
(derived from the canonical cell key, never the draw position) must hold.

Reference analogues: hex/grid/GridSearch.java (the walk), water/Job.java
(progress), hex/faulttolerance/Recovery.java (resume without retraining).

The member-death and cancel->resume drills live in scripts/chaos.py
(``kill_search_member``) and the multiprocess SIGKILL tier."""

import os
import time

import numpy as np
import pytest

from h2o3_tpu.cluster import dkv as cdkv
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.keyed import KeyedStore
from h2o3_tpu.models.framework import Job
from h2o3_tpu.models.glm import GLM, GLMParameters
from h2o3_tpu.models.grid import (
    GridSearch,
    SearchCriteria,
    _random_discrete,
    cell_key,
    cell_seed,
    metric_value,
)

pytestmark = pytest.mark.leaks_keys


def _wait_for(cond, timeout=10.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


def _frame(seed=0, n=400):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    logit = X @ np.array([1.0, -2.0, 0.5])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
    cols = [Column(f"x{i}", X[:, i]) for i in range(3)]
    cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
    return Frame(cols)


def _rows(grid):
    """(canonical hp key, metric) per model, walk order — the bit-exact
    leaderboard signature (model keys are uuid-fresh, so not compared)."""
    return [(cell_key(hp), metric_value(m, "auto")[0])
            for hp, m in zip(grid.hyper_params, grid.models)]


def _counter(name, **labels):
    from h2o3_tpu.util import telemetry

    c = telemetry.REGISTRY.get(name)
    if c is None:
        return 0.0
    return c.value(**labels) if labels else c.total()


@pytest.fixture()
def three_clouds():
    """A formed 3-node cloud with DKV + DTask planes installed; node 0
    is the process-local (caller) cloud for the duration."""
    clouds = []
    for i in range(3):
        c = Cloud("searchcloud", f"sn{i}", hb_interval=0.05)
        s = KeyedStore()
        cdkv.install(c, s)
        ctasks.install(c)
        clouds.append(c)
    seeds = [c.info.addr for c in clouds]
    try:
        for c in clouds:
            c.start([a for a in seeds if a != c.info.addr])
        _wait_for(lambda: all(c.size() == 3 for c in clouds),
                  msg="3-node cloud formation")
        set_local_cloud(clouds[0])
        yield clouds
    finally:
        set_local_cloud(None)
        for c in clouds:
            try:
                c.stop()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# determinism contract: canonical cell keys and derived seeds


class TestCellSeeds:
    def test_cell_key_canonical(self):
        # key order in the dict never changes the canonical key
        assert (cell_key({"alpha": 0.5, "lambda_": 0.01})
                == cell_key({"lambda_": 0.01, "alpha": 0.5}))
        assert (cell_key({"alpha": 0.5})
                != cell_key({"alpha": 1.0}))

    def test_cell_seed_position_independent(self):
        hps = [{"alpha": a, "lambda_": l}
               for a in (0.0, 0.5, 1.0) for l in (0.0, 0.01)]
        seeds_fwd = [cell_seed(7, cell_key(hp)) for hp in hps]
        seeds_rev = [cell_seed(7, cell_key(hp)) for hp in reversed(hps)]
        assert seeds_fwd == list(reversed(seeds_rev))
        # distinct cells get distinct seeds; unseeded search derives none
        assert len(set(seeds_fwd)) == len(hps)
        assert cell_seed(None, cell_key(hps[0])) is None
        assert cell_seed(-1, cell_key(hps[0])) is None

    def test_cell_params_derive_from_key_not_draw_order(self):
        gs = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial", seed=7),
            {"alpha": [0.0, 0.5], "lambda_": [0.0, 0.01]})
        hps = list(gs._walk())
        fwd = {cell_key(hp): gs._cell_params(hp).seed for hp in hps}
        rev = {cell_key(hp): gs._cell_params(hp).seed
               for hp in reversed(hps)}
        assert fwd == rev
        assert all(s not in (-1, None) for s in fwd.values())

    def test_explicit_seed_in_hyper_grid_honored(self):
        gs = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial", seed=7),
            {"seed": [11, 22]})
        assert gs._cell_params({"seed": 11}).seed == 11
        assert gs._cell_params({"seed": 22}).seed == 22

    def test_random_discrete_walk_sequence_unchanged(self):
        """Regression pin: keying per-cell seeds on the canonical hp key
        must NOT have perturbed the seeded walk itself — the combo
        sequence for a fixed seed is part of the resume contract."""
        hyper = {"alpha": [0.0, 0.5, 1.0], "lambda_": [0.0, 0.01]}
        got = list(_random_discrete(hyper, 123))
        assert got == [
            {"alpha": 0.0, "lambda_": 0.0},
            {"alpha": 0.5, "lambda_": 0.01},
            {"alpha": 0.0, "lambda_": 0.01},
            {"alpha": 1.0, "lambda_": 0.01},
            {"alpha": 0.5, "lambda_": 0.0},
            {"alpha": 1.0, "lambda_": 0.0},
        ]
        # sampling without replacement covers the whole product space
        assert len({cell_key(hp) for hp in got}) == 6


# ---------------------------------------------------------------------------
# wire format: frames out once, model blobs back


class TestWireFormat:
    def test_frame_payload_roundtrip(self):
        from h2o3_tpu.cluster.search import frame_payload, frame_restore

        fr = _frame(3, n=50)
        fr2 = frame_restore(frame_payload(fr))
        assert fr2.names == fr.names
        for nm in fr.names:
            a, b = fr.col(nm), fr2.col(nm)
            assert a.type == b.type and a.domain == b.domain
            assert np.array_equal(np.asarray(a.data), np.asarray(b.data))

    def test_model_blob_roundtrip(self):
        from h2o3_tpu.cluster.search import model_from_blob, model_to_blob

        fr = _frame(4, n=120)
        m = GLM(GLMParameters(
            response_column="y", family="binomial", seed=5)).train(fr)
        m2 = model_from_blob(model_to_blob(m))
        p1 = m.predict(fr).col("pp").numeric_view()
        p2 = m2.predict(fr).col("pp").numeric_view()
        assert np.array_equal(p1, p2)


# ---------------------------------------------------------------------------
# the fan-out itself (in-process clouds, real sockets)


class TestDistributedGrid:
    HYPER = {"alpha": [0.0, 0.5, 1.0], "lambda_": [0.01, 0.1]}

    def _gs(self, criteria=None):
        return GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial",
                          seed=7, nfolds=2),
            self.HYPER, search_criteria=criteria)

    def test_search_cloud_gates(self, three_clouds):
        from h2o3_tpu.cluster.search import search_cloud

        assert search_cloud() is three_clouds[0]
        os.environ["H2O3_TPU_SEARCH_DIST"] = "0"
        try:
            assert search_cloud() is None
        finally:
            os.environ.pop("H2O3_TPU_SEARCH_DIST", None)

    def test_bit_identical_to_single_node(self, three_clouds):
        fr = _frame(0)
        os.environ["H2O3_TPU_SEARCH_DIST"] = "0"
        try:
            base = _rows(self._gs().train(fr))
        finally:
            os.environ.pop("H2O3_TPU_SEARCH_DIST", None)

        cells0 = _counter("cluster_search_cells_total")
        done0 = _counter("cluster_search_progress_total", status="done")
        job = Job("dist grid").start()
        grid = self._gs().train(fr, job=job)

        assert len(grid.models) == 6 and not grid.failures
        assert _rows(grid) == base  # bit-identical, canonical walk order
        # every cell trained exactly once, somewhere in the cloud
        # (in-process clouds share one telemetry registry)
        assert _counter("cluster_search_cells_total") - cells0 == 6.0
        # per-model completion streamed back over search_progress
        assert (_counter("cluster_search_progress_total", status="done")
                - done0 == 6.0)
        assert job.progress == 1.0
        assert job.progress_msg is not None
        assert "6/6" in job.progress_msg

    def test_progress_accessor_live_and_job_updates(self, three_clouds):
        from h2o3_tpu.cluster.search import search_progress

        fr = _frame(1)
        job = Job("dist grid progress").start()
        grid = self._gs().train(fr, job=job)
        prog = search_progress(grid.grid_id)
        assert prog is not None
        assert prog["done"] == prog["total"] == 6
        assert prog["errors"] == 0
        # cells really spread: more than one member reported completions
        assert len(prog["by_member"]) >= 2

    def test_random_discrete_distributed_matches_local(self, three_clouds):
        fr = _frame(2)
        crit = SearchCriteria(strategy="RandomDiscrete", seed=123,
                              max_models=4)
        os.environ["H2O3_TPU_SEARCH_DIST"] = "0"
        try:
            base = _rows(self._gs(crit).train(fr))
        finally:
            os.environ.pop("H2O3_TPU_SEARCH_DIST", None)
        grid = self._gs(crit).train(fr)
        assert len(grid.models) == 4
        assert _rows(grid) == base
