"""Rapids query fusion tests — fused-vs-interpreted bit-identity.

Every prim in the fusibility registry gets a parity case over a
special-values frame (NaN, ±inf, ±0.0, negative zero-crossing div/mod
operands); the oracle is the op-at-a-time interpreter itself with
``H2O3_TPU_RAPIDS_FUSION=0``. Identity is *bitwise* (uint64 views; the
one exemption is NaN payloads — both-NaN cells compare equal). The
registry-completeness test plus the scripts/check_telemetry.py lint keep
this table in lockstep with the FUSIBLE registry.
"""

import os

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids import Session, exec_rapids
from h2o3_tpu.rapids.prims import FUSIBLE
from h2o3_tpu.util import telemetry

# rapids assignments leave frames in the DKV by design (see test_rapids.py)
pytestmark = pytest.mark.leaks_keys


def _counter(name, **labels):
    c = telemetry.REGISTRY.get(name)
    return float(c.value(**labels)) if c is not None else 0.0


def bits_equal(a, b):
    """Bitwise float64 equality, NaN-payload exempt (both-NaN is equal)."""
    a = np.atleast_1d(np.asarray(a, dtype=np.float64))
    b = np.atleast_1d(np.asarray(b, dtype=np.float64))
    if a.shape != b.shape:
        return False
    bad = (a.view(np.uint64) != b.view(np.uint64)) & ~(
        np.isnan(a) & np.isnan(b))
    return not bad.any()


def assert_same_val(ref, got, ctx=""):
    assert ref.kind == got.kind, (ctx, ref, got)
    if ref.is_frame():
        rf, gf = ref.value, got.value
        assert [c.name for c in rf.columns] == [c.name for c in gf.columns], ctx
        for rc, gc in zip(rf.columns, gf.columns):
            assert rc.type is gc.type, (ctx, rc.name)
            if rc.type in (ColType.STR, ColType.UUID):
                assert list(rc.data) == list(gc.data), (ctx, rc.name)
            else:
                assert rc.domain == gc.domain, (ctx, rc.name)
                assert bits_equal(rc.numeric_view(), gc.numeric_view()), \
                    (ctx, rc.name)
    else:
        assert bits_equal(np.asarray(ref.value, dtype=np.float64),
                          np.asarray(got.value, dtype=np.float64)), ctx


def run_both(sess, expr):
    """(interpreted, fused, fused_delta, fallback_delta) for one expr."""
    prev = os.environ.get("H2O3_TPU_RAPIDS_FUSION")
    try:
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "0"
        ref = exec_rapids(expr, sess)
        f0 = _counter("rapids_fusion_total", result="fused")
        b0 = _counter("rapids_fusion_total", result="fallback")
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
        got = exec_rapids(expr, sess)
    finally:
        if prev is None:
            os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
        else:
            os.environ["H2O3_TPU_RAPIDS_FUSION"] = prev
    return (ref, got,
            _counter("rapids_fusion_total", result="fused") - f0,
            _counter("rapids_fusion_total", result="fallback") - b0)


def _special_frame():
    # div/mod sign rules, inf dividends, signed zeros, NaN propagation
    a = [1.5, -2.5, np.nan, np.inf, -np.inf, 0.0, -0.0, 3.0, -3.0, 7.25,
         -7.25, 2.0, 1e300, -1e-300, 5.0, -5.5, -1.0, 0.5, -0.25, 9.0]
    b = [2.0, -3.0, 1.0, 2.0, 2.0, -0.0, 0.0, -2.0, np.nan, np.inf,
         -np.inf, 0.5, 1e-300, 1e300, -5.0, 5.5, np.inf, -0.0, 4.0, -9.0]
    rng = np.random.default_rng(11)
    ra = rng.standard_normal(200) * 10
    rb = rng.standard_normal(200) * 10
    ra[::13] = np.nan
    rb[::17] = np.nan
    return Frame([
        Column("a", np.concatenate([a, ra]), ColType.NUM),
        Column("b", np.concatenate([b, rb]), ColType.NUM),
    ])


@pytest.fixture
def sess():
    s = Session()
    s.assign("pf", _special_frame())
    return s


#: one fused-region expression per fusible prim (registry lint: every
#: FUSIBLE name must appear quoted here with a parity case)
PARITY_CASES = {
    "+": '(+ (cols_py pf 0) (cols_py pf 1))',
    "-": '(- (cols_py pf 0) (cols_py pf 1))',
    "*": '(* (cols_py pf 0) (cols_py pf 1))',
    "/": '(/ (cols_py pf 0) (cols_py pf 1))',
    "%": '(% (cols_py pf 0) (cols_py pf 1))',
    "%%": '(%% (cols_py pf 0) (cols_py pf 1))',
    "intDiv": '(intDiv (cols_py pf 0) (cols_py pf 1))',
    "%/%": '(%/% (cols_py pf 0) (cols_py pf 1))',
    "==": '(== (cols_py pf 0) (cols_py pf 1))',
    "!=": '(!= (cols_py pf 0) (cols_py pf 1))',
    "<": '(< (cols_py pf 0) (cols_py pf 1))',
    "<=": '(<= (cols_py pf 0) (cols_py pf 1))',
    ">": '(> (cols_py pf 0) (cols_py pf 1))',
    ">=": '(>= (cols_py pf 0) (cols_py pf 1))',
    "&": '(& (cols_py pf 0) (cols_py pf 1))',
    "&&": '(&& (cols_py pf 0) (cols_py pf 1))',
    "|": '(| (cols_py pf 0) (cols_py pf 1))',
    "||": '(|| (cols_py pf 0) (cols_py pf 1))',
    "not": '(not (cols_py pf 0))',
    "ifelse": '(ifelse (> (cols_py pf 0) 0) (cols_py pf 0) (cols_py pf 1))',
    "abs": '(abs (cols_py pf 0))',
    "ceiling": '(ceiling (cols_py pf 0))',
    "floor": '(floor (cols_py pf 0))',
    "trunc": '(trunc (cols_py pf 0))',
    "round": '(round (cols_py pf 0) 0)',
    "sqrt": '(sqrt (cols_py pf 0))',
    "sign": '(sign (cols_py pf 0))',
    "sgn": '(sgn (cols_py pf 0))',
    "sin": '(sin (cols_py pf 0))',
    "cos": '(cos (cols_py pf 0))',
    "sinpi": '(sinpi (cols_py pf 0))',
    "cospi": '(cospi (cols_py pf 0))',
    "none": '(none (cols_py pf 0))',
    "is.na": '(is.na (cols_py pf 0))',
    "cols": '(* (cols pf [0]) 2)',
    "cols_py": '(* (cols_py pf 1) 2)',
    "max": '(max (* (cols_py pf 0) 2))',
    "maxNA": '(maxNA (* (cols_py pf 0) 2))',
    "min": '(min (* (cols_py pf 0) 2))',
    "minNA": '(minNA (* (cols_py pf 0) 2))',
    "sum": '(sum (* (cols_py pf 0) 2))',
    "sumNA": '(sumNA (* (cols_py pf 0) 2))',
    "prod": '(prod (* (cols_py pf 0) 0))',
    "prodNA": '(prodNA (ifelse (is.na (cols_py pf 0)) 1 2))',
    "mean": '(mean (* (cols_py pf 0) 2))',
}


def test_registry_completeness():
    """Every fusible prim has a parity case and vice versa — a new
    fusible registration without a case here fails the build (this test
    AND the scripts/check_telemetry.py lint)."""
    assert set(PARITY_CASES) == set(FUSIBLE)


@pytest.mark.parametrize("name", sorted(PARITY_CASES))
def test_parity(sess, name):
    ref, got, fused, fallback = run_both(sess, PARITY_CASES[name])
    assert fused >= 1 and fallback == 0, (name, fused, fallback)
    assert_same_val(ref, got, ctx=name)


# -- broadcasting ------------------------------------------------------------

def test_frame_scalar_broadcast(sess):
    ref, got, fused, _ = run_both(sess, '(* (+ pf 1) 2)')
    assert fused >= 1
    assert_same_val(ref, got)


def test_scalar_frame_broadcast(sess):
    ref, got, fused, _ = run_both(sess, '(- 1 (/ 2 pf))')
    assert fused >= 1
    assert_same_val(ref, got)


def test_rhs_single_col_broadcast(sess):
    """frame ⊕ 1-col frame: the single rhs column pairs with every lhs
    column and output names come from the lhs."""
    ref, got, fused, _ = run_both(sess, '(* (+ pf 0) (cols_py pf 1))')
    assert fused >= 1
    assert [c.name for c in got.value.columns] == ["a", "b"]
    assert_same_val(ref, got)


def test_lhs_single_col_broadcast_raises_identically(sess):
    """1-col frame ⊕ frame: H2O names every output column after the lhs
    column — duplicate names, which the Frame constructor rejects. The
    fused path must surface the same error."""
    for flag in ("0", "1"):
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = flag
        with pytest.raises(ValueError, match="duplicate column names"):
            exec_rapids('(* (cols_py pf 1) (+ pf 0))', sess)
    os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)


def test_row_mismatch_falls_back(sess):
    """Fusing across frames of different heights is never attempted —
    the interpreter's 1-row broadcast (or error) semantics win."""
    one = Frame([Column("k", np.array([2.0]), ColType.NUM)])
    sess.assign("one", one)
    ref, got, _, fallback = run_both(sess, '(* (+ (cols_py pf 0) one) 3)')
    assert fallback >= 1
    assert_same_val(ref, got)


# -- fallback at the region boundary -----------------------------------------

def test_mixed_tree_boundary(sess):
    """A non-fusible transcendental mid-tree fractures the region: the
    chain above it fuses with the log1p result as a leaf, bit-identically."""
    expr = '(sum (* (log1p (abs (cols_py pf 0))) 2))'
    ref, got, fused, fallback = run_both(sess, expr)
    assert fused >= 1 and fallback == 0
    assert_same_val(ref, got)


def test_pow_never_fuses(sess):
    """XLA pow differs from numpy in last-ulp cases, so ^ is deliberately
    not fusible — it evaluates as an interpreter leaf."""
    assert "^" not in FUSIBLE
    ref, got, _, _ = run_both(sess, '(sum (* (^ (cols_py pf 0) 2) 3))')
    assert_same_val(ref, got)


def test_scalar_leaf(sess):
    """An interior reducer is a region leaf: its NUM result enters the
    fused program as a runtime scalar slot, not a recompile per value."""
    expr = '(* (- (cols_py pf 0) (mean (cols_py pf 0))) 2)'
    ref, got, fused, fallback = run_both(sess, expr)
    assert fused >= 1 and fallback == 0
    assert_same_val(ref, got)


def test_str_arithmetic_raises_identically(sess):
    fs = Frame([
        Column("x", np.arange(8, dtype=np.float64), ColType.NUM),
        Column("s", np.array(["p", "q", None, "r"] * 2, dtype=object),
               ColType.STR),
    ])
    sess.assign("fs", fs)
    errs = []
    for flag in ("0", "1"):
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = flag
        with pytest.raises(Exception) as ei:
            exec_rapids('(* (+ fs 1) 2)', sess)
        errs.append(type(ei.value))
    os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
    assert errs[0] is errs[1]


def test_str_passthrough_select(sess):
    """String columns ride through pure column selection untouched."""
    fs = Frame([
        Column("x", np.arange(6, dtype=np.float64), ColType.NUM),
        Column("s", np.array(["p", "q", None, "r", "p", "q"], dtype=object),
               ColType.STR),
    ])
    sess.assign("fs2", fs)
    ref, got, _, _ = run_both(sess, '(cols (cols fs2 [0 1]) [1])')
    assert_same_val(ref, got)
    assert got.value.col(0).type is ColType.STR


def test_cat_codes_and_domain(sess):
    cat = Column("c", np.array([0, 1, -1, 2, 1, 0] * 4, dtype=np.int32),
                 ColType.CAT, domain=["lo", "mid", "hi"])
    fc = Frame([Column("x", np.arange(24, dtype=np.float64), ColType.NUM), cat])
    sess.assign("fc", fc)
    # numeric compute over a CAT column runs on its codes (NA at -1)
    ref, got, fused, _ = run_both(sess, '(* (+ (cols_py fc 1) 1) 2)')
    assert fused >= 1
    assert_same_val(ref, got)
    # bare pass-through keeps the Column type and domain
    ref, got, _, _ = run_both(sess, '(cols (cols fc [0 1]) [1])')
    assert_same_val(ref, got)
    assert got.value.col(0).type is ColType.CAT
    assert got.value.col(0).domain == ["lo", "mid", "hi"]
    # both-CAT ifelse may be domain-preserving: must fall back, identically
    ref, got, _, fallback = run_both(
        sess, '(ifelse (> (cols_py fc 0) 10) (cols_py fc 1) (cols_py fc 1))')
    assert fallback >= 1
    assert_same_val(ref, got)


# -- caching -----------------------------------------------------------------

def test_warm_path_zero_recompile(sess):
    expr = ('(sum (ifelse (> (+ (cols_py pf 0) (cols_py pf 1)) 0) '
            '(cols_py pf 0) (cols_py pf 1)))')
    os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
    try:
        cold = exec_rapids(expr, sess)
        snap = {
            "jit_miss": _counter("mapreduce_jit_cache_total",
                                 op="map_batches", result="miss"),
            "plan_miss": _counter("mapreduce_plan_cache_total",
                                  op="rapids_fusion", result="miss"),
            "upload": _counter("shard_bytes_total"),
            "dev_miss": _counter("devcache_requests_total",
                                 kind="frame_table", result="miss"),
        }
        warm = exec_rapids(expr, sess)
        assert bits_equal(cold.value, warm.value)
        assert _counter("mapreduce_jit_cache_total",
                        op="map_batches", result="miss") == snap["jit_miss"]
        assert _counter("mapreduce_plan_cache_total",
                        op="rapids_fusion", result="miss") == snap["plan_miss"]
        assert _counter("shard_bytes_total") == snap["upload"]
        assert _counter("devcache_requests_total",
                        kind="frame_table", result="miss") == snap["dev_miss"]
    finally:
        os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)


def test_devcache_invalidation_after_assign(sess):
    """Rectangle assignment bumps column versions: the next fused dispatch
    re-uploads and sees the new data (never stale device state)."""
    rng = np.random.default_rng(3)
    vf = Frame([Column("u", rng.standard_normal(64), ColType.NUM),
                Column("v", rng.standard_normal(64), ColType.NUM)])
    sess.assign("vf", vf)
    expr = '(sum (* (+ (cols_py vf 0) (cols_py vf 1)) 2))'
    os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
    try:
        before = exec_rapids(expr, sess)
        exec_rapids(expr, sess)  # warm
        miss0 = _counter("devcache_requests_total",
                         kind="frame_table", result="miss")
        exec_rapids('(tmp= vf (:= vf (* (cols_py vf 0) 0.5) [0] _))', sess)
        after = exec_rapids(expr, sess)
        assert _counter("devcache_requests_total",
                        kind="frame_table", result="miss") > miss0
    finally:
        os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
    os.environ["H2O3_TPU_RAPIDS_FUSION"] = "0"
    try:
        ref = exec_rapids(expr, sess)
    finally:
        os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
    assert bits_equal(ref.value, after.value)
    assert not bits_equal(before.value, after.value)


# -- knobs -------------------------------------------------------------------

def test_kill_switch(sess):
    expr = '(sum (* (+ (cols_py pf 0) (cols_py pf 1)) 2))'
    os.environ["H2O3_TPU_RAPIDS_FUSION"] = "0"
    try:
        f0 = _counter("rapids_fusion_total", result="fused")
        b0 = _counter("rapids_fusion_total", result="fallback")
        out = exec_rapids(expr, sess)
        assert _counter("rapids_fusion_total", result="fused") == f0
        assert _counter("rapids_fusion_total", result="fallback") == b0
    finally:
        os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
    ref, got, _, _ = run_both(sess, expr)
    assert bits_equal(out.value, ref.value)


def test_min_ops_gate(sess):
    os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
    try:
        f0 = _counter("rapids_fusion_total", result="fused")
        exec_rapids('(+ pf 1)', sess)  # 1 op < default min of 2: interpreted
        assert _counter("rapids_fusion_total", result="fused") == f0
        os.environ["H2O3_TPU_RAPIDS_FUSION_MIN_OPS"] = "5"
        exec_rapids('(* (+ pf 1) 2)', sess)  # 2 ops < 5: interpreted
        assert _counter("rapids_fusion_total", result="fused") == f0
        os.environ["H2O3_TPU_RAPIDS_FUSION_MIN_OPS"] = "2"
        exec_rapids('(* (+ pf 1) 2)', sess)
        assert _counter("rapids_fusion_total", result="fused") == f0 + 1
    finally:
        os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
        os.environ.pop("H2O3_TPU_RAPIDS_FUSION_MIN_OPS", None)


def test_fusible_registry_emitters():
    """Mirror of the scripts/check_telemetry.py lint: compute-kind fusible
    prims always carry an emitter (FuseSpec enforces it at registration)."""
    for name, spec in FUSIBLE.items():
        if spec.kind in ("binop", "uniop", "ifelse"):
            assert spec.emit is not None, name
        else:
            assert spec.kind in ("select", "reduce"), name
